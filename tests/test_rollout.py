"""Streaming plan rollout (ISSUE 12, docs/ROLLOUT.md): wave packing
under per-wave transfer caps, the epoch-fenced rollout state machine,
canary/rollback semantics, mid-rollout re-plans against the
partially-moved ground truth, the serve endpoints over real HTTP, the
durable record, and the ``kao_rollout_*`` metric families — including
the acceptance proofs: every wave's caps asserted straight off the
move graph, rollback restoring the pre-rollout assignment bit-exactly,
and every transition visible simultaneously in the plan store, flight
records, trace spans, and metrics."""

import json
import os
import signal
import threading
from pathlib import Path

import pytest

from kafka_assignment_optimizer_tpu import serve as srv
from kafka_assignment_optimizer_tpu.models.cluster import (
    Assignment,
    Topology,
)
from kafka_assignment_optimizer_tpu.obs import flight as oflight
from kafka_assignment_optimizer_tpu.obs import trace as otrace
from kafka_assignment_optimizer_tpu.resilience.budget import Budget
from kafka_assignment_optimizer_tpu.rollout import exec as rexec
from kafka_assignment_optimizer_tpu.rollout import state as rstate
from kafka_assignment_optimizer_tpu.rollout import waves as rwaves
from kafka_assignment_optimizer_tpu.watch import manager as wman
from kafka_assignment_optimizer_tpu.watch import store as wstore

GOLDEN = Path(__file__).parent / "golden" / "waves"


def _assign(P=8, B=4, rf=2, off=0):
    return {
        "version": 1,
        "partitions": [
            {"topic": "t", "partition": p,
             "replicas": [(p + i + off) % B for i in range(rf)]}
            for p in range(P)
        ],
    }


def _bootstrap(epoch=1, B=4, P=8, **extra):
    return {
        "type": "bootstrap", "epoch": epoch,
        "assignment": _assign(P=P, B=B),
        "brokers": list(range(B)), "topology": "even-odd", **extra,
    }


def _stub_solve_fn(state, prev_plan, budget):
    """Deterministic rebalancer: round-robin every partition over the
    eligible brokers — real moves whenever eligibility changes."""
    elig = sorted(state.brokers)
    parts = []
    for p in state.assignment.partitions:
        rf = len(p.replicas) or 2
        reps = [elig[(p.partition + i) % len(elig)] for i in range(rf)]
        parts.append({"topic": p.topic, "partition": p.partition,
                      "replicas": reps})
    return ({"version": 1, "partitions": parts},
            {"feasible": True, "replica_moves": 1})


def _registry(store=None, caps=(2, 8)):
    reg = wman.WatchRegistry(_stub_solve_fn, store, window_s=0.0)
    mgr = rexec.RolloutManager(reg, store, broker_cap=caps[0],
                               rack_cap=caps[1])
    return reg, mgr


def _wave_peaks(wave_moves, rack_of):
    """Per-wave peak broker/rack loads recomputed INDEPENDENTLY from
    the move graph (adds + source), never read back from the packer's
    own accounting."""
    bl, rl = {}, {}
    for m in wave_moves:
        adds = m.adds if hasattr(m, "adds") else m["adds"]
        source = m.source if hasattr(m, "source") else m["source"]
        for b in adds:
            bl[b] = bl.get(b, 0) + 1
            r = rack_of(b)
            rl[r] = rl.get(r, 0) + 1
            if source is not None:
                bl[source] = bl.get(source, 0) + 1
    return (max(bl.values(), default=0), max(rl.values(), default=0))


# --------------------------------------------------------------------------
# waves: the transfer model and both packers
# --------------------------------------------------------------------------


def test_moves_of_transfer_model():
    cur = Assignment.from_dict(_assign())
    tgt = Assignment.from_dict(_assign(off=1))
    moves = rwaves.moves_of(cur, tgt)
    assert len(moves) == 8
    m0 = moves[0]
    assert m0.old == (0, 1) and m0.new == (1, 2)
    assert m0.adds == (2,)          # only genuinely new replicas copy
    assert m0.source == 0           # the current leader streams it
    assert m0.leader_changed        # 0 -> 1
    # initial placement (empty current list): inbound only, no source
    tgt2 = Assignment.from_dict(_assign())
    cur2 = Assignment.from_dict(_assign())
    cur2.partitions[0].replicas = []
    m = rwaves.moves_of(cur2, tgt2)[0]
    assert m.source is None and m.adds == (0, 1)
    assert not m.leader_changed


def test_pack_waves_caps_coverage_and_leader_order():
    cur = Assignment.from_dict(_assign(P=12))
    tgt = Assignment.from_dict(_assign(P=12, off=1))
    topo = Topology.even_odd(range(4))
    caps = rwaves.WaveCaps(broker=2, rack=4)
    plan = rwaves.pack_waves(cur, tgt, topo, caps=caps)
    assert plan.makespan >= 2
    # every move appears exactly once across the waves
    seen = [(m.topic, m.partition) for w in plan.waves
            for m in w.moves]
    assert sorted(seen) == sorted(
        (m.topic, m.partition) for m in rwaves.moves_of(cur, tgt))
    # the cap contract, asserted off the move graph per wave
    for w in plan.waves:
        pb, pr = _wave_peaks(w.moves, topo.rack)
        assert pb <= plan.caps.broker and pr <= plan.caps.rack
    assert rwaves.verify_caps(plan)
    # leader-changing moves come LAST within each wave
    for w in plan.waves:
        flags = [m.leader_changed for m in w.ordered_moves()]
        assert flags == sorted(flags)
    # determinism: same inputs, same packing
    again = rwaves.pack_waves(cur, tgt, topo, caps=caps)
    assert again.to_dict() == plan.to_dict()


def test_caps_below_single_move_floor_are_raised():
    # partition 0 replaces both followers: the source (leader 0)
    # streams 2 copies, so its own broker load is 2 — above a cap of 1,
    # and a single partition's copy can never split across waves
    cur = Assignment.from_dict(_assign(P=2, B=6, rf=3))
    cur.partitions[0].replicas = [0, 1, 2]
    tgt = Assignment.from_dict(_assign(P=2, B=6, rf=3))
    tgt.partitions[0].replicas = [0, 4, 5]
    plan = rwaves.pack_waves(
        cur, tgt, None, caps=rwaves.WaveCaps(broker=1, rack=1))
    assert plan.caps.raised
    assert plan.caps.broker >= 2
    assert rwaves.verify_caps(plan)


def test_scored_packer_no_worse_than_greedy_and_budget_safe():
    cur = Assignment.from_dict(_assign(P=24, B=6, rf=2))
    tgt = Assignment.from_dict(_assign(P=24, B=6, rf=2, off=2))
    topo = Topology.even_odd(range(6))
    caps = rwaves.WaveCaps(broker=2, rack=4)
    greedy = rwaves.pack_waves(cur, tgt, topo, caps=caps)
    scored = rwaves.pack_waves(cur, tgt, topo, caps=caps,
                               packer="scored", seed=3)
    assert scored.score <= greedy.score
    assert rwaves.verify_caps(scored)
    # an expired budget stops the race but lane 0 always completes
    b = Budget(None)
    b.cancel()
    under = rwaves.pack_waves(cur, tgt, topo, caps=caps,
                              packer="scored", budget=b)
    assert under.makespan >= 1 and rwaves.verify_caps(under)
    with pytest.raises(ValueError):
        rwaves.pack_waves(cur, tgt, topo, packer="nope")


def test_wave_json_is_upstream_schema_with_leader_moves_last():
    cur = Assignment.from_dict(_assign())
    tgt = Assignment.from_dict(_assign(off=1))
    plan = rwaves.pack_waves(cur, tgt, None,
                             caps=rwaves.WaveCaps(broker=64, rack=256))
    doc = rexec.wave_json(plan.waves[0])
    assert set(doc) == {"version", "partitions"}
    assert doc["version"] == 1
    for p in doc["partitions"]:
        assert set(p) == {"topic", "partition", "replicas"}
        assert all(isinstance(b, int) for b in p["replicas"])
    # the dialect round-trips through the model's own parser
    Assignment.from_dict(doc)


# --------------------------------------------------------------------------
# CLI --emit-waves: per-wave files, byte-golden
# --------------------------------------------------------------------------


def test_emit_waves_golden_bytes(tmp_path):
    """The satellite pin: wave files are byte-compatible with the
    upstream reassignment schema — goldened on a fixed (current, plan)
    pair so solver nondeterminism can never flake the bytes."""
    cur = Assignment.from_dict(_assign(P=4, B=4, rf=2))
    tgt = Assignment.from_dict(_assign(P=4, B=4, rf=2, off=1))
    plan = rwaves.pack_waves(cur, tgt, Topology.even_odd(range(4)),
                             caps=rwaves.WaveCaps(broker=1, rack=4))
    got = {
        f"wave-{w.index:03d}.json":
            json.dumps(rexec.wave_json(w), indent=2) + "\n"
        for w in plan.waves
    }
    golden_files = sorted(p.name for p in GOLDEN.glob("wave-*.json"))
    assert golden_files == sorted(got), (
        "wave schedule changed; regenerate tests/golden/waves/ and "
        "review the diff deliberately"
    )
    for name in golden_files:
        assert (GOLDEN / name).read_text() == got[name], name


def test_emit_waves_cli(tmp_path):
    """The CLI path end to end: --emit-waves writes files that parse
    as reassignment JSON and byte-match the library packing of the
    CLI's own input/output pair."""
    import subprocess
    import sys

    cur = _assign(P=8, B=4, rf=2)
    inp = tmp_path / "cur.json"
    inp.write_text(json.dumps(cur))
    outp = tmp_path / "plan.json"
    waves_dir = tmp_path / "waves"
    r = subprocess.run(
        [sys.executable, "-m", "kafka_assignment_optimizer_tpu",
         "-i", str(inp), "-o", str(outp), "--broker-list", "0-2",
         "--topology", "even-odd", "--solver", "milp",
         "--emit-waves", str(waves_dir), "--wave-broker-cap", "1",
         "--report"],
        cwd="/root/repo",
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rep = json.loads(r.stderr[r.stderr.index("{"):])
    assert rep["waves"]["makespan"] >= 1
    files = sorted(waves_dir.glob("wave-*.json"))
    assert len(files) == rep["waves"]["makespan"]
    # byte-compat: the files equal the library packing of the same pair
    plan = rwaves.pack_waves(
        Assignment.from_dict(cur),
        Assignment.from_json(outp.read_text()),
        Topology.even_odd(range(4)),
        caps=rwaves.WaveCaps(broker=1, rack=16),
    )
    for f, w in zip(files, plan.waves):
        assert f.read_text() == \
            json.dumps(rexec.wave_json(w), indent=2) + "\n"
    # applying the waves in file order reproduces the plan exactly
    state = {(p["topic"], p["partition"]): p["replicas"]
             for p in cur["partitions"]}
    for f in files:
        for p in json.loads(f.read_text())["partitions"]:
            state[(p["topic"], p["partition"])] = p["replicas"]
    final = json.loads(outp.read_text())
    assert state == {(p["topic"], p["partition"]): p["replicas"]
                     for p in final["partitions"]}


# --------------------------------------------------------------------------
# state machine + fencing (store provably untouched)
# --------------------------------------------------------------------------


def test_state_machine_transitions_and_conflicts(tmp_path):
    store = wstore.PlanStore(tmp_path)
    reg, mgr = _registry(store)
    reg.handle_event("c", _bootstrap())
    reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                           "brokers": [3]})
    v = mgr.command("c", "start", {"epoch": 1})
    assert v["status"] == "planned" and v["waves"] >= 2
    # start over an active rollout is a conflict, not a new rollout
    with pytest.raises(rstate.RolloutConflict):
        mgr.command("c", "start", {"epoch": 2})
    v = mgr.command("c", "advance", {"epoch": 2})
    assert v["status"] == "canary" and v["current_wave"] is not None
    # advancing past canary demands the operator's verdict
    with pytest.raises(rstate.RolloutError):
        mgr.command("c", "advance", {"epoch": 3})
    v = mgr.command("c", "pause", {"epoch": 3})
    assert v["status"] == "paused"
    with pytest.raises(rstate.RolloutConflict):
        mgr.command("c", "pause", {"epoch": 4})
    v = mgr.command("c", "advance", {"epoch": 4})   # resume
    assert v["status"] == "canary"
    v = mgr.command("c", "advance", {"epoch": 5, "canary_ok": True})
    assert v["status"] in ("advancing", "done")
    assert v["applied"] == [0]
    # commands need an epoch at all
    with pytest.raises(rstate.RolloutError):
        mgr.command("c", "advance", {})


def test_canary_failure_rolls_back(tmp_path):
    store = wstore.PlanStore(tmp_path)
    reg, mgr = _registry(store)
    reg.handle_event("c", _bootstrap())
    reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                           "brokers": [3]})
    base = reg.get_cluster("c")["assignment"]
    mgr.command("c", "start", {"epoch": 1})
    base_post_rewind = reg.get_cluster("c")["assignment"]
    mgr.command("c", "advance", {"epoch": 2})
    v = mgr.command("c", "advance", {"epoch": 3, "canary_ok": False})
    assert v["status"] == "rolled_back"
    assert v["rollback_reason"] == "canary_fail"
    assert mgr.snapshot()["canary_fail_total"] == 1
    # the canary wave was never applied, so truth is the rewound base
    assert reg.get_cluster("c")["assignment"] == base_post_rewind


def test_stale_epoch_fenced_without_touching_store(tmp_path):
    store = wstore.PlanStore(tmp_path)
    reg, mgr = _registry(store)
    reg.handle_event("c", _bootstrap())
    reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                           "brokers": [3]})
    mgr.command("c", "start", {"epoch": 5})
    mgr.command("c", "advance", {"epoch": 6})
    path = tmp_path / "rollout" / "c.json"
    before = path.read_bytes()
    n_cmds = mgr.snapshot()["commands_total"]
    with pytest.raises(rstate.RolloutFenced) as e:
        mgr.command("c", "advance", {"epoch": 6, "canary_ok": True})
    assert e.value.got == 6 and e.value.current == 6
    # THE fencing proof: the fence counter moved, nothing else did,
    # and the durable record is byte-identical
    snap = mgr.snapshot()
    assert snap["fenced_total"] == 1
    assert snap["commands_total"] == n_cmds
    assert path.read_bytes() == before
    # the stream continues at the correct epoch
    v = mgr.command("c", "advance", {"epoch": 7, "canary_ok": True})
    assert v["applied"] == [0]


def test_rollback_restores_pre_rollout_bit_exact(tmp_path):
    store = wstore.PlanStore(tmp_path)
    reg, mgr = _registry(store)
    reg.handle_event("c", _bootstrap(P=12))
    reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                           "brokers": [3]})
    v = mgr.command("c", "start", {"epoch": 1})
    base = reg.get_cluster("c")["assignment"]  # post-rewind pre-rollout
    assert v["waves"] >= 2
    mgr.command("c", "advance", {"epoch": 2})
    v = mgr.command("c", "advance", {"epoch": 3, "canary_ok": True})
    # ground truth moved away from base...
    assert reg.get_cluster("c")["assignment"] != base
    v = mgr.command("c", "rollback", {"epoch": 4})
    assert v["status"] == "rolled_back"
    assert v["inverse_waves"]  # the inverse reassignments, newest first
    # ...and rollback restored it BIT-EXACTLY
    assert reg.get_cluster("c")["assignment"] == base
    assert json.dumps(reg.get_cluster("c")["assignment"],
                      sort_keys=True) == json.dumps(base, sort_keys=True)


def test_second_start_after_done_does_not_rewind(tmp_path):
    """Review fix: once waves have EXECUTED the plan, the pre-plan
    rewind point is consumed — a later start must base on the real
    ground truth (zero waves, immediately done), never rewind executed
    state to the stale pre-rollout base."""
    store = wstore.PlanStore(tmp_path)
    reg, mgr = _registry(store)
    reg.handle_event("c", _bootstrap())
    reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                           "brokers": [3]})
    mgr.command("c", "start", {"epoch": 1})
    mgr.command("c", "advance", {"epoch": 2})
    v = mgr.command("c", "advance", {"epoch": 3, "canary_ok": True})
    ep = 4
    while v["status"] == "advancing":
        v = mgr.command("c", "advance", {"epoch": ep})
        ep += 1
    assert v["status"] == "done"
    executed = reg.get_cluster("c")["assignment"]
    assert executed == reg.get_cluster("c")["plan"]
    v2 = mgr.command("c", "start", {"epoch": ep})
    assert v2["status"] == "done" and v2["waves"] == 0
    # the ground truth was NOT rewound to the pre-rollout base
    assert reg.get_cluster("c")["assignment"] == executed
    # and a post-rollout delta solve merges its plan normally again
    reg.handle_event("c", {"type": "broker_add", "epoch": 3,
                           "brokers": [3]})
    info = reg.get_cluster("c")
    assert info["assignment"] == info["plan"]


def test_rebootstrap_voids_active_rollout(tmp_path):
    """Review fix: a re-bootstrap re-declares the world — the active
    rollout's record is generation-fenced (commands refuse, a fresh
    start is admitted) and the registry's ground-truth hold is
    released."""
    store = wstore.PlanStore(tmp_path)
    reg, mgr = _registry(store)
    reg.handle_event("c", _bootstrap())
    reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                           "brokers": [3]})
    mgr.command("c", "start", {"epoch": 1})
    mgr.command("c", "advance", {"epoch": 2})
    reg.handle_event("c", _bootstrap(epoch=3))  # generation bump
    with pytest.raises(rstate.RolloutConflict) as e:
        mgr.command("c", "advance", {"epoch": 3, "canary_ok": True})
    assert "re-bootstrap" in str(e.value)
    # the hold is released: a delta solve merges normally again
    reg.handle_event("c", {"type": "broker_drain", "epoch": 4,
                           "brokers": [3]})
    info = reg.get_cluster("c")
    assert info["assignment"] == info["plan"]
    # and a fresh start (new generation) is admitted
    v = mgr.command("c", "start", {"epoch": 3})
    assert v["status"] in ("planned", "done")


def test_restart_ignores_dead_generation_hold(tmp_path):
    """Review fix: a restart must NOT resurrect the ground-truth hold
    from a rollout record that predates a re-bootstrap — the cluster
    would silently stop merging plans forever."""
    store = wstore.PlanStore(tmp_path)
    reg, mgr = _registry(store)
    reg.handle_event("c", _bootstrap())
    reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                           "brokers": [3]})
    mgr.command("c", "start", {"epoch": 1})
    mgr.command("c", "advance", {"epoch": 2})     # active, gen 0
    reg.handle_event("c", _bootstrap(epoch=3))    # gen 1
    # restart: fresh registry over the same store
    reg2, mgr2 = _registry(store)
    reg2.handle_event("c", {"type": "broker_drain", "epoch": 4,
                            "brokers": [3]})
    info = reg2.get_cluster("c")
    # the plan merged normally: the stale record's hold did not stick
    assert info["assignment"] == info["plan"]


def test_start_failure_after_rewind_releases_hold(tmp_path,
                                                  monkeypatch):
    """Review fix: a start that fails AFTER begin_execution (failed
    save, bad packer) must release the hold — no record exists to
    drive the cluster, so plan merges must keep working."""
    store = wstore.PlanStore(tmp_path)
    reg, mgr = _registry(store)
    reg.handle_event("c", _bootstrap())
    reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                           "brokers": [3]})

    def boom(cluster_id, record):
        raise OSError("disk full")

    monkeypatch.setattr(store, "save_rollout", boom)
    with pytest.raises(OSError):
        mgr.command("c", "start", {"epoch": 1})
    monkeypatch.undo()
    assert mgr.get("c") is None
    # the hold was released: the next delta solve merges its plan
    reg.handle_event("c", {"type": "broker_add", "epoch": 3,
                           "brokers": [3]})
    info = reg.get_cluster("c")
    assert info["assignment"] == info["plan"]


def test_failed_save_does_not_fence_the_retry(tmp_path, monkeypatch):
    """Review fix: commands mutate a working copy, swapped in only
    after the persist succeeds — a failed save leaves memory and disk
    agreeing, so the client's retry of the SAME epoch is admitted,
    not 409d on a command that was never durably recorded."""
    store = wstore.PlanStore(tmp_path)
    reg, mgr = _registry(store)
    reg.handle_event("c", _bootstrap())
    reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                           "brokers": [3]})
    mgr.command("c", "start", {"epoch": 1})
    mgr.command("c", "advance", {"epoch": 2})
    real_save = store.save_rollout
    calls = {"n": 0}

    def flaky(cluster_id, record):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("disk full")
        return real_save(cluster_id, record)

    monkeypatch.setattr(store, "save_rollout", flaky)
    with pytest.raises(OSError):
        mgr.command("c", "advance", {"epoch": 3, "canary_ok": True})
    # memory did not advance past disk: the SAME epoch retries clean
    v = mgr.command("c", "advance", {"epoch": 3, "canary_ok": True})
    assert v["applied"] == [0] and v["rollout_epoch"] == 3


def test_rollback_unplaces_partitions_grown_mid_rollout(tmp_path):
    """Review fix: a partition created by a mid-rollout growth event
    and placed by a post-replan wave rolls back to the EMPTY replica
    list growth declared — not to its rollout-assigned placement."""
    store = wstore.PlanStore(tmp_path)
    reg, mgr = _registry(store, caps=(2, 8))
    reg.handle_event("c", _bootstrap(P=8))
    reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                           "brokers": [3]})
    mgr.command("c", "start", {"epoch": 1})
    base = reg.get_cluster("c")["assignment"]
    mgr.command("c", "advance", {"epoch": 2})
    mgr.command("c", "advance", {"epoch": 3, "canary_ok": True})
    # growth mid-rollout: two new partitions appear empty and the
    # replanned remaining waves place them
    reg.handle_event("c", {"type": "partition_growth", "epoch": 3,
                           "topic": "t", "add": 2})
    v = mgr.get("c")
    assert v["replans"] == 1
    # apply every remaining wave so the placements land
    ep = 4
    while v["status"] in ("canary", "advancing"):
        p = {"epoch": ep}
        if v["status"] == "canary":
            p["canary_ok"] = True
        v = mgr.command("c", "advance", p)
        ep += 1
        if len(v["applied"]) >= 2 and v["status"] == "advancing":
            break
    grown = {("t", 8), ("t", 9)}
    truth = {(p["topic"], p["partition"]): p["replicas"]
             for p in reg.get_cluster("c")["assignment"]["partitions"]}
    placed = {k for k in grown if truth[k]}
    v = mgr.command("c", "rollback", {"epoch": ep})
    assert v["status"] == "rolled_back"
    after = {(p["topic"], p["partition"]): p["replicas"]
             for p in reg.get_cluster("c")["assignment"]["partitions"]}
    # grown partitions are UN-placed (their pre-rollout truth)...
    for k in placed:
        assert after[k] == [], (k, after[k])
    # ...and every base partition is bit-exactly back at base
    base_by = {(p["topic"], p["partition"]): p["replicas"]
               for p in base["partitions"]}
    for k, reps in base_by.items():
        assert after[k] == reps, k


def test_record_survives_restart_same_wave_same_epoch(tmp_path):
    store = wstore.PlanStore(tmp_path)
    reg, mgr = _registry(store)
    reg.handle_event("c", _bootstrap())
    reg.handle_event("c", {"type": "broker_drain", "epoch": 2,
                           "brokers": [3]})
    mgr.command("c", "start", {"epoch": 1})
    mgr.command("c", "advance", {"epoch": 2})
    v = mgr.command("c", "advance", {"epoch": 3, "canary_ok": True})
    # a fresh registry + manager over the same store (process restart)
    reg2, mgr2 = _registry(store)
    v2 = mgr2.get("c")
    assert v2["status"] == v["status"]
    assert v2["wave_index"] == v["wave_index"]
    assert v2["rollout_epoch"] == 3
    # the fence survives the restart too
    with pytest.raises(rstate.RolloutFenced):
        mgr2.command("c", "advance", {"epoch": 3})
    # a corrupt rollout record is ignored, never trusted
    path = tmp_path / "rollout" / "c.json"
    path.write_text(path.read_text()[:-20] + "}")
    reg3, mgr3 = _registry(store)
    assert mgr3.get("c") is None


# --------------------------------------------------------------------------
# serve layer: the endpoints over real HTTP — the acceptance flow
# --------------------------------------------------------------------------


@pytest.fixture
def rollout_env(tmp_path, monkeypatch):
    monkeypatch.setitem(srv.WATCH, "dir", str(tmp_path / "watch"))
    monkeypatch.setitem(srv.WATCH, "registry", None)
    monkeypatch.setitem(srv.WATCH, "window_s", 0.0)
    monkeypatch.setitem(srv.ROLLOUT, "manager", None)
    monkeypatch.setitem(srv.ROLLOUT, "broker_cap", 1)
    monkeypatch.setitem(srv.ROLLOUT, "rack_cap", 8)
    server = srv.make_server(port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield (tmp_path, f"http://127.0.0.1:{server.server_address[1]}")
    server.shutdown()
    server.server_close()
    srv.WATCH["registry"] = None
    srv.ROLLOUT["manager"] = None


def _http(method, url, payload=None, timeout=60):
    import urllib.error
    import urllib.request

    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"},
        method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _counter(text: str, name: str) -> float:
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[-1])
    raise AssertionError(f"{name} not in /metrics")


def test_http_e2e_certify_canary_waves_caps_and_surfaces(rollout_env):
    """The acceptance flow over real HTTP: submit -> certify -> start
    -> canary -> advance through >= 3 waves, every wave's transfer
    caps asserted from the move graph against the live pre-wave ground
    truth; all transitions visible simultaneously in the plan store,
    flight records, trace spans, and kao_rollout_* metrics."""
    tmp_path, url = rollout_env
    st, _ = _http("POST", url + "/clusters/prod/events",
                  _bootstrap(B=4, P=8))
    assert st == 200
    st, body = _http("POST", url + "/clusters/prod/events",
                     {"type": "broker_drain", "epoch": 2,
                      "brokers": [3]})
    assert st == 200
    assert body["report"]["feasible"]
    assert body["report"]["proven_optimal"]  # certified plan
    moves_planned = body["report"]["replica_moves"]
    assert moves_planned >= 3

    st, view = _http("POST", url + "/clusters/prod/rollout/start",
                     {"epoch": 1})
    assert st == 200 and view["status"] == "planned"
    assert view["waves"] >= 3                     # >= 3 waves at cap 1
    assert view["caps"] == {"broker": 1, "rack": 8, "raised": False}
    topo = Topology.even_odd(range(4))

    def advance(ep, **extra):
        # the ground truth BEFORE the wave applies: sources derive
        # from it, so cap math is checked against the real move graph
        _, info = _http("GET", url + "/clusters/prod")
        truth = {(p["topic"], p["partition"]): p["replicas"]
                 for p in info["assignment"]["partitions"]}
        _, v = _http("GET", url + "/clusters/prod/rollout")
        wave = v["current_wave"]
        if wave is not None:
            bl, rl = {}, {}
            for p in wave["partitions"]:
                old = truth[(p["topic"], p["partition"])]
                adds = [b for b in p["replicas"] if b not in set(old)]
                src = old[0] if old else None
                for b in adds:
                    bl[b] = bl.get(b, 0) + 1
                    r = topo.rack(b)
                    rl[r] = rl.get(r, 0) + 1
                    if src is not None:
                        bl[src] = bl.get(src, 0) + 1
            assert max(bl.values(), default=0) <= v["caps"]["broker"]
            assert max(rl.values(), default=0) <= v["caps"]["rack"]
        st, v = _http("POST", url + "/clusters/prod/rollout/advance",
                      {"epoch": ep, **extra})
        assert st == 200, v
        return v

    view = advance(2)                       # planned -> canary
    assert view["status"] == "canary"
    view = advance(3, canary_ok=True)       # canary verified, applied
    ep = 4
    while view["status"] == "advancing":
        view = advance(ep)
        ep += 1
    assert view["status"] == "done"
    assert len(view["applied"]) == view["waves"] >= 3
    # the executed truth IS the certified plan
    _, info = _http("GET", url + "/clusters/prod")
    assert info["assignment"] == info["plan"]

    # -- simultaneous visibility on all four surfaces ------------------
    # 1) plan store: the durable rollout record, fingerprint-verified
    rec = wstore.PlanStore(srv.WATCH["dir"]).load_rollout("prod")
    assert rec is not None and rec["status"] == "done"
    assert rec["applied"] == list(range(view["waves"]))
    # 2) flight records: one kind="rollout" per transition, and
    # 3) trace spans: each record's trace_id resolves in the ring
    recs = [r for r in oflight.recent(kind="rollout")
            if r.get("cluster") == "prod"]
    assert len(recs) >= view["waves"] + 2   # start + canary + waves
    assert {r["command"] for r in recs} >= {"start", "advance"}
    tid = recs[-1]["trace_id"]
    rep = otrace.RECENT.get(tid)
    assert rep is not None and rep["name"] == "rollout"
    # 4) metrics: the counter families moved together
    import urllib.request

    with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
        text = r.read().decode()
    assert _counter(text, "kao_rollout_started_total") >= 1
    assert _counter(text, "kao_rollout_waves_applied_total") \
        == view["waves"]
    assert _counter(text, "kao_rollout_completed_total") >= 1
    assert _counter(text, "kao_rollout_active") == 0
    from tests.test_metrics_format import validate_prometheus

    validate_prometheus(text)


def test_http_mid_rollout_event_replans_remaining_waves(rollout_env):
    """A broker_remove mid-rollout re-solves against the PARTIALLY-
    MOVED ground truth (never clobbered by the new plan) and re-packs
    the remaining waves toward it — warm-started through the same
    watch machinery."""
    tmp_path, url = rollout_env
    _http("POST", url + "/clusters/prod/events", _bootstrap(B=5, P=10))
    st, _ = _http("POST", url + "/clusters/prod/events",
                  {"type": "broker_drain", "epoch": 2, "brokers": [4]})
    assert st == 200
    st, view = _http("POST", url + "/clusters/prod/rollout/start",
                     {"epoch": 1})
    assert st == 200 and view["waves"] >= 2
    _http("POST", url + "/clusters/prod/rollout/advance", {"epoch": 2})
    st, view = _http("POST", url + "/clusters/prod/rollout/advance",
                     {"epoch": 3, "canary_ok": True})
    assert st == 200
    _, mid = _http("GET", url + "/clusters/prod")
    truth_mid = mid["assignment"]
    # the mid-rollout cluster event: a broker is GONE
    st, body = _http("POST", url + "/clusters/prod/events",
                     {"type": "broker_remove", "epoch": 3,
                      "brokers": [4]})
    assert st == 200
    _, after = _http("GET", url + "/clusters/prod")
    # the rollout holds the ground truth: the new plan did NOT merge
    assert after["assignment"] == truth_mid
    assert after["plan"] == body["assignment"]
    st, view = _http("GET", url + "/clusters/prod/rollout")
    assert view["replans"] == 1
    assert view["status"] in ("canary", "advancing")
    # kept waves keep their indices; remaining waves chase the new plan
    assert view["applied"] == [0]
    ep = 4
    while view["status"] in ("canary", "advancing"):
        extra = ({"canary_ok": True} if view["status"] == "canary"
                 else {})
        st, view = _http("POST",
                         url + "/clusters/prod/rollout/advance",
                         {"epoch": ep, **extra})
        assert st == 200, view
        ep += 1
    assert view["status"] == "done"
    _, info = _http("GET", url + "/clusters/prod")
    assert info["assignment"] == info["plan"]
    assert _counter(_metrics_text(url),
                    "kao_rollout_replans_total") >= 1


def _metrics_text(url: str) -> str:
    import urllib.request

    with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
        return r.read().decode()


def test_http_rollback_and_stale_epoch_409(rollout_env):
    tmp_path, url = rollout_env
    _http("POST", url + "/clusters/prod/events", _bootstrap())
    _http("POST", url + "/clusters/prod/events",
          {"type": "broker_drain", "epoch": 2, "brokers": [3]})
    st, view = _http("POST", url + "/clusters/prod/rollout/start",
                     {"epoch": 1})
    assert st == 200
    _, info = _http("GET", url + "/clusters/prod")
    base = info["assignment"]
    _http("POST", url + "/clusters/prod/rollout/advance", {"epoch": 2})
    st, view = _http("POST", url + "/clusters/prod/rollout/advance",
                     {"epoch": 3, "canary_ok": True})
    assert st == 200 and view["applied"] == [0]
    # stale rollout epoch: structured 409, store untouched
    store_path = (Path(srv.WATCH["dir"]) / "rollout" / "prod.json")
    before = store_path.read_bytes()
    st, err = _http("POST", url + "/clusters/prod/rollout/advance",
                    {"epoch": 3})
    assert st == 409
    assert err["reason"] == "stale_rollout_epoch"
    assert err["current_rollout_epoch"] == 3
    assert err["expected_min_epoch"] == 4
    assert store_path.read_bytes() == before
    # rollback from a non-terminal wave restores base bit-exactly
    st, view = _http("POST", url + "/clusters/prod/rollout/rollback",
                     {"epoch": 4})
    assert st == 200 and view["status"] == "rolled_back"
    _, info = _http("GET", url + "/clusters/prod")
    assert info["assignment"] == base
    # commands on a terminal rollout are 409 bad_state
    st, err = _http("POST", url + "/clusters/prod/rollout/advance",
                    {"epoch": 5})
    assert st == 409 and err["reason"] == "bad_state"
    # GET on a cluster with no rollout is a 404
    st, err = _http("GET", url + "/clusters/other/rollout")
    assert st == 404


def test_cluster_named_rollout_stays_readable(rollout_env):
    """Review fix: the rollout GET route must not shadow a cluster
    legitimately named 'rollout'."""
    tmp_path, url = rollout_env
    st, _ = _http("POST", url + "/clusters/rollout/events",
                  _bootstrap())
    assert st == 200
    st, info = _http("GET", url + "/clusters/rollout")
    assert st == 200 and info["cluster_id"] == "rollout"
    # ...and that cluster's own rollout record is still addressable
    st, err = _http("GET", url + "/clusters/rollout/rollout")
    assert st == 404  # none started yet — the route resolved, though


def test_rollout_404_and_conflict_mapping(rollout_env):
    tmp_path, url = rollout_env
    # unknown cluster: 404 from start
    st, err = _http("POST", url + "/clusters/ghost/rollout/start",
                    {"epoch": 1})
    assert st == 404
    # known cluster, no certified plan yet -> 409 (bootstrap solves a
    # plan, so fabricate the edge via a registry with no plan)
    st, err = _http("POST", url + "/clusters/ghost/rollout/advance",
                    {"epoch": 1})
    assert st == 409 and err["reason"] == "bad_state"
    # malformed body
    st, err = _http("POST", url + "/clusters/ghost/rollout/start",
                    {"epoch": -1})
    assert st == 400
    # malformed caps are the documented 400 too, never a 422
    _http("POST", url + "/clusters/capbad/events", _bootstrap())
    _http("POST", url + "/clusters/capbad/events",
          {"type": "broker_drain", "epoch": 2, "brokers": [3]})
    st, err = _http("POST", url + "/clusters/capbad/rollout/start",
                    {"epoch": 1, "broker_cap": "abc"})
    assert st == 400, (st, err)


# --------------------------------------------------------------------------
# real HTTP, real SIGKILL: mid-wave restart resumes at the same wave
# with the same epoch (the PR-7 fresh-port restart harness)
# --------------------------------------------------------------------------


@pytest.mark.soak
@pytest.mark.slow  # ~30 s: two server spawns around a real SIGKILL.
# The nightly soak runs it; the same durability semantics stay
# tier-1-covered in-process by
# test_record_survives_restart_same_wave_same_epoch.
def test_sigkill_mid_wave_restart_resumes_same_wave(tmp_path):
    import subprocess
    import sys
    import time as _time

    from tests.test_watch import _free_port, _http as _whttp

    def start_server(port, watch_dir, timeout=120):
        # the PR-7 harness, plus --rollout-broker-cap 1 so the drain
        # packs into >= 3 waves (a 1-wave rollout would be done before
        # the kill)
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "kafka_assignment_optimizer_tpu.serve",
             "--port", str(port), "--watch-dir", str(watch_dir),
             "--workers", "1", "--max-solve-s", "300",
             "--rollout-broker-cap", "1"],
            cwd="/root/repo",
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        deadline = _time.time() + timeout
        url = f"http://127.0.0.1:{port}"
        while _time.time() < deadline:
            if proc.poll() is not None:
                raise AssertionError(
                    f"server died rc={proc.returncode}")
            try:
                status, _ = _whttp("GET", url + "/healthz", timeout=5)
                if status == 200:
                    return proc, url
            except Exception:
                _time.sleep(0.2)
        proc.kill()
        raise AssertionError("server never became healthy")

    watch = tmp_path / "watch"
    proc, url = start_server(_free_port(), watch)
    try:
        st, _ = _whttp("POST", url + "/clusters/prod/events",
                       _bootstrap(B=4, P=8))
        assert st == 200
        st, _ = _whttp("POST", url + "/clusters/prod/events",
                       {"type": "broker_drain", "epoch": 2,
                        "brokers": [3]})
        assert st == 200
        st, v = _whttp("POST", url + "/clusters/prod/rollout/start",
                       {"epoch": 1})
        assert st == 200
        st, v = _whttp("POST", url + "/clusters/prod/rollout/advance",
                       {"epoch": 2})
        assert st == 200 and v["status"] == "canary"
        st, v = _whttp("POST", url + "/clusters/prod/rollout/advance",
                       {"epoch": 3, "canary_ok": True})
        assert st == 200
        wave_index, status, epoch = (v["wave_index"], v["status"],
                                     v["rollout_epoch"])
    finally:
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()
    # restart on a FRESH port (the killed listener's socket can linger)
    proc, url = start_server(_free_port(), watch)
    try:
        st, v2 = _whttp("GET", url + "/clusters/prod/rollout")
        assert st == 200
        assert v2["wave_index"] == wave_index
        assert v2["status"] == status
        assert v2["rollout_epoch"] == epoch
        # the fence survived the kill: a stale command still 409s
        st, err = _whttp("POST",
                         url + "/clusters/prod/rollout/advance",
                         {"epoch": epoch})
        assert st == 409 and err["reason"] == "stale_rollout_epoch"
        # and the stream continues from exactly where it stood
        st, v3 = _whttp("POST",
                        url + "/clusters/prod/rollout/advance",
                        {"epoch": epoch + 1})
        assert st == 200
        assert v3["applied"][: len(v2["applied"])] == v2["applied"]
    finally:
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()
