"""The bench harness's robustness contract (round-1 postmortem).

Round 1's only benchmark artifact was a crash log: the site TPU plugin
failed init and ``bench.py`` died before printing anything parsable
(VERDICT.md "what's weak" #1). The contract now under test:

1. ``python bench.py`` ALWAYS prints exactly one parsable JSON line on
   stdout — success or not — with ``platform`` recorded.
2. Backend init is probed in a subprocess under a timeout; a hung or
   broken accelerator falls back to CPU and still lands a number.
3. The headline carries both cold and warm wall-clock (compile split).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import pytest
from pathlib import Path

BENCH = str(Path(__file__).resolve().parent.parent / "bench.py")


def _run(args, env_extra, timeout=300):
    env = dict(os.environ)
    env.update(env_extra)
    r = subprocess.run(
        [sys.executable, BENCH, *args],
        env=env, timeout=timeout, capture_output=True, text=True,
    )
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert lines, f"no stdout; stderr tail: {r.stderr[-500:]}"
    # the driver records only a ~2000-char stdout TAIL: exactly one line,
    # small enough to survive the capture whole (VERDICT r3 item 1)
    import bench

    assert len(lines) == 1, f"extra stdout lines: {lines[:-1]}"
    assert len(lines[-1]) <= bench.STDOUT_BUDGET, (
        f"stdout line {len(lines[-1])} bytes"
    )
    return r, json.loads(lines[-1])


def test_smoke_demo_prints_parsable_line():
    r, line = _run(
        ["--smoke", "--scenario", "demo", "--headline-only"],
        {"JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0
    assert line["unit"] == "s"
    assert line["platform"] == "cpu"
    assert line["feasible"] is True
    assert line["moves"] <= line["min_moves_lb"] or not line["feasible"]
    assert line["vs_baseline"] > 0
    # cold/warm split (VERDICT item 7): cold includes compile, warm does not
    assert line["cold_wall_clock_s"] >= line["value"]
    # the full child report (compile split etc.) is stderr-only now
    assert "[bench] DETAIL " in r.stderr


def test_failure_still_prints_parsable_line():
    """Starve both the probe and the child of time: the harness must not
    crash or hang — it must emit vs_baseline 0.0 with an error field."""
    r, line = _run(
        ["--smoke", "--scenario", "demo", "--headline-only"],
        {
            "JAX_PLATFORMS": "",  # force a real probe
            "KAO_PROBE_TIMEOUT": "0.2",  # probe cannot possibly finish
            "KAO_BENCH_TIMEOUT": "0.2",  # nor can the solve child
        },
        timeout=120,
    )
    assert r.returncode == 0
    assert line["vs_baseline"] == 0.0
    assert "error" in line
    assert "platform" in line


@pytest.mark.soak
@pytest.mark.slow  # ~2.5 min: a full bench --smoke (8 scenario children
# + repeat probes) in subprocesses; the driver exercises bench.py
# directly every round, so the tier-1 gate doesn't need to re-run it
def test_default_run_embeds_full_results_table():
    """The driver's default invocation must evidence EVERY scenario in
    the single stdout line (VERDICT r2 item 3): a compact scenarios
    array plus the fresh-process cold_cached_wall_clock_s probe — and
    the whole line must fit the driver's tail capture (r3 item 1)."""
    from kafka_assignment_optimizer_tpu.utils import gen

    r, line = _run(["--smoke"], {"JAX_PLATFORMS": "cpu"}, timeout=900)
    assert r.returncode == 0
    schema = line["rows_schema"].split(",")
    rows = {row[0]: dict(zip(schema, row)) for row in line["scenarios"]}
    assert set(rows) == set(gen.SCENARIOS)
    for name, row in rows.items():
        assert row["engine"] != "error", f"{name}: {row}"
        assert row["feasible"] == 1, f"{name}: {row}"
        assert row["moves"] >= row["min_moves_lb"] >= 0
        assert isinstance(row["warm_s"], float)
        assert isinstance(row["cold_s"], float)
        assert row["proved_optimal"] in (0, 1)
        assert row["constructed"] in (0, 1)
    # the headline row is the same run the headline metric quotes
    assert rows["decommission"]["warm_s"] == line["value"]
    # fresh-process cold probe against the populated compile cache
    assert isinstance(line["cold_cached_wall_clock_s"], float)
    assert line["cold_cached_wall_clock_s"] > 0


def test_seed_time_budget_at_headline_scale():
    """VERDICT r1 weak #9: the greedy seed is host-side Python and its
    docstring promises sub-second-ish behavior at the headline size.
    Pin a generous regression bound so an accidental O(P*B) loop in the
    seed shows up as a test failure, not a silent wall-clock regression
    in the bench artifact."""
    import time

    from kafka_assignment_optimizer_tpu.models.instance import (
        build_instance,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed
    from kafka_assignment_optimizer_tpu.utils import gen

    sc = gen.SCENARIOS["decommission"]()  # 256 brokers / 10k partitions
    inst = build_instance(sc.current, sc.broker_list, sc.topology,
                          sc.target_rf)
    t0 = time.perf_counter()
    a = greedy_seed(inst)
    seed_s = time.perf_counter() - t0
    assert a.shape == inst.a0.shape
    # very generous: measured ~0.9 s cold on the bench host; an
    # accidental O(P*B) Python loop would take minutes, so 15 s catches
    # the regression class without flaking on contended CI runners
    assert seed_s < 15.0, f"greedy_seed took {seed_s:.2f}s at headline scale"


def test_compact_mesh_block_shapes():
    """The --mesh-bench stdout block (ISSUE 19): the compactor carries
    the comparator-gated keys + the spec->lanes/s curve, and the error
    path still lands a parsable block."""
    import bench

    rm = {
        "n_devices": 8, "lanes": 4, "bucket": [32, 8, 90, 3],
        "parity_ok": True, "chosen": "8x1",
        "default_lanes_per_s": 4.0, "best_spec": "4x2",
        "best_lanes_per_s": 5.0, "lane_scaling": 1.25,
        "search_s": 9.0, "search_evals": 3,
        "single_core_parity_expected": True,
        "specs": [
            {"spec": "8x1", "lanes_per_s": 4.0, "warm_s": 1.0,
             "parity_vs_default": True},
            {"spec": "4x2", "lanes_per_s": 5.0, "warm_s": 0.8,
             "parity_vs_default": True},
        ],
    }
    out = bench._compact_mesh(rm, None)
    assert out["parity_ok"] is True
    assert out["curve"] == {"8x1": 4.0, "4x2": 5.0}
    assert out["best_spec"] == "4x2"
    assert out["single_core_parity_expected"] is True
    # a dead child still prints a parsable, bounded error block
    err = bench._compact_mesh(None, "boom " * 100)
    assert "error" in err and len(err["error"]) <= 120
