"""Cross-process causal tracing (ISSUE 15, docs/OBSERVABILITY.md
"Distributed traces").

Four layers:

- the W3C ``traceparent`` codec: compact-ID round-trip, foreign-ID
  adoption, malformed headers tolerated as fresh roots (remote link
  dropped, counters moved, never an exception);
- remote-parented roots + serve-side adoption: a ``handle_submit``
  carrying a propagated context runs the solve under the ROUTER's
  trace ID and records the parent span; absent the header, the
  ambient trace is byte-for-byte the PR 3 behavior;
- the tail-retention policy (``KAO_TRACE_TAIL``): slow / degraded /
  chaos-touched / hedged traces keep their full trees, fast-clean
  traces survive only the deterministic head sample — decisions
  replayable under a seeded load;
- the router+2-worker join (the acceptance shape): a hedged request
  through a real ``Router`` over two scripted workers yields ONE
  trace ID whose ``GET /debug/traces/<id>`` merges the router's
  route/attempt/hedge spans with BOTH workers' solve trees (the hedge
  duplicate included) and exports one multi-process Perfetto file.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kafka_assignment_optimizer_tpu.fleet import affinity
from kafka_assignment_optimizer_tpu.fleet.health import FleetTracker
from kafka_assignment_optimizer_tpu.fleet.router import (
    Router,
    make_router_server,
)
from kafka_assignment_optimizer_tpu.models.cluster import demo_assignment
from kafka_assignment_optimizer_tpu.obs import causal as ocausal
from kafka_assignment_optimizer_tpu.obs import chrome as ochrome
from kafka_assignment_optimizer_tpu.obs import trace as otrace


# --------------------------------------------------------------------------
# codec
# --------------------------------------------------------------------------


def test_traceparent_roundtrip_compact_id():
    header = otrace.inject("abcd1234abcd1234", "00ff00ff00ff00ff")
    assert header == (
        "00-0000000000000000abcd1234abcd1234-00ff00ff00ff00ff-01"
    )
    ctx = otrace.extract(header)
    assert ctx == ("abcd1234abcd1234", "00ff00ff00ff00ff")


def test_traceparent_foreign_full_width_id_adopted_verbatim():
    foreign = "00-" + "a1" * 16 + "-" + "b2" * 8 + "-01"
    ctx = otrace.extract(foreign)
    assert ctx is not None
    assert ctx.trace_id == "a1" * 16       # full 32-hex, no stripping
    assert ctx.span_id == "b2" * 8
    # and it re-injects as itself
    assert otrace.inject(ctx.trace_id, ctx.span_id) == foreign


def test_traceparent_malformed_tolerated_as_new_root():
    before = dict(otrace.PROPAGATION)
    bad = [
        "garbage",
        "00-zz" + "0" * 30 + "-" + "b" * 16 + "-01",   # non-hex
        "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",     # reserved ver
        "00-" + "0" * 32 + "-" + "b" * 16 + "-01",     # zero trace
        "00-" + "a" * 32 + "-" + "0" * 16 + "-01",     # zero span
        "00-" + "a" * 31 + "-" + "b" * 16 + "-01",     # short
    ]
    for header in bad:
        assert otrace.extract(header) is None, header
    # absent headers are not "malformed" — just absent
    assert otrace.extract(None) is None
    assert otrace.extract("") is None
    after = dict(otrace.PROPAGATION)
    assert after["malformed"] - before["malformed"] == len(bad)
    assert after["extracted"] == before["extracted"]


def test_inject_reads_ambient_context_and_assigns_span_id():
    assert otrace.inject() is None  # no active trace, nothing to send
    tr = otrace.begin(True, name="request")
    try:
        with otrace.span("attempt") as sp:
            header = otrace.inject()
            assert header is not None
            ctx = otrace.extract(header)
            assert ctx.trace_id == tr.trace_id
            # the ambient span got a lazily-assigned ID, and the
            # header carries exactly it
            assert ctx.span_id == sp.span_id
    finally:
        otrace.finish(tr)


def test_begin_remote_parent_marks_server_root():
    tr = otrace.begin("cafe01", remote_parent="beef0000beef0000")
    rep = otrace.finish(tr)
    attrs = rep["spans"]["attrs"]
    assert attrs["parent_span_id"] == "beef0000beef0000"
    assert attrs["span_kind"] == "server"
    # without a remote parent the root is untouched (ambient behavior
    # unchanged when no header arrives)
    rep2 = otrace.finish(otrace.begin("cafe02", name="request"))
    assert "parent_span_id" not in (rep2["spans"].get("attrs") or {})


# --------------------------------------------------------------------------
# serve-side adoption
# --------------------------------------------------------------------------


def _milp_payload():
    return {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "solver": "milp",
    }


def test_handle_submit_adopts_propagated_context():
    from kafka_assignment_optimizer_tpu.obs import flight as oflight
    from kafka_assignment_optimizer_tpu.serve import handle_submit

    ctx = otrace.RemoteContext("feedfacefeedface", "1234abcd1234abcd")
    out = handle_submit(_milp_payload(), trace_ctx=ctx)
    # the response echoes the ROUTER's trace id, not a fresh one
    assert out["trace_id"] == "feedfacefeedface"
    rep = otrace.RECENT.get("feedfacefeedface")
    assert rep is not None
    attrs = rep["spans"]["attrs"]
    assert attrs["parent_span_id"] == "1234abcd1234abcd"
    assert attrs["span_kind"] == "server"
    # the flight record is stamped with the same (propagated) trace id
    assert any(
        r.get("trace_id") == "feedfacefeedface"
        for r in oflight.recent(64)
    )


def test_handle_submit_without_header_is_fresh_root():
    from kafka_assignment_optimizer_tpu.serve import handle_submit

    out = handle_submit(_milp_payload())
    tid = out["trace_id"]
    assert tid and tid != "feedfacefeedface"
    rep = otrace.RECENT.get(tid)
    assert "parent_span_id" not in (rep["spans"].get("attrs") or {})


# --------------------------------------------------------------------------
# tail-based retention
# --------------------------------------------------------------------------


def test_tail_spec_typo_fails_loudly():
    with pytest.raises(ValueError):
        otrace.TailPolicy.from_spec("head=4,windoow=9")
    with pytest.raises(ValueError):
        otrace.TailPolicy.from_spec("head=lots")


def _fast_report(tid, name="request", wall=0.01):
    return {"trace_id": tid, "name": name, "wall_s": wall,
            "spans": {"name": name, "attrs": {}}}


def test_tail_policy_deterministic_and_signal_complete():
    policy = otrace.TailPolicy.from_spec(
        "head=8,window=128,quantile=0.99,min=32")
    import random

    rng = random.Random(7)
    tids = [format(rng.getrandbits(64), "016x") for _ in range(300)]
    # warmup + steady load of fast-clean traces with rare 100x spikes
    slow_ids, decisions = set(), {}
    for i, tid in enumerate(tids):
        wall = 0.01 + rng.random() * 0.002
        if i > 100 and i % 50 == 0:
            wall = 1.0
            slow_ids.add(tid)
        decisions[tid] = policy.decide(_fast_report(tid, wall=wall))
    # every slow trace kept in full
    assert all(decisions[t] == "full" for t in slow_ids)
    # fast-clean traces: kept iff the deterministic hash says so
    for tid, d in decisions.items():
        if tid in slow_ids or d == "full":
            continue
        expect = ("head" if int(tid[-8:], 16) % 8 == 0 else "dropped")
        assert d == expect, (tid, d)
    # and a REPLAY of the same load makes identical decisions
    replay = otrace.TailPolicy.from_spec(
        "head=8,window=128,quantile=0.99,min=32")
    rng = random.Random(7)
    # consume the SAME id draws so the wall sequence replays exactly
    assert [format(rng.getrandbits(64), "016x")
            for _ in range(300)] == tids
    for i, tid in enumerate(tids):
        wall = 0.01 + rng.random() * 0.002
        if i > 100 and i % 50 == 0:
            wall = 1.0
        assert replay.decide(_fast_report(tid, wall=wall)) == \
            decisions[tid]
    # degraded / chaos / hedged / errored traces are ALWAYS full
    keep_shapes = [
        {"spans": {"name": "request",
                   "spans": [{"name": "degrade",
                              "attrs": {"rung": "pallas_to_xla"}}]}},
        {"spans": {"name": "request", "spans": [{"name": "chaos"}]}},
        {"spans": {"name": "request", "attrs": {"hedged": True}}},
        {"spans": {"name": "request",
                   "spans": [{"name": "ladder",
                              "attrs": {"error": "boom"}}]}},
    ]
    for shape in keep_shapes:
        rep = {"trace_id": "00", "name": "request", "wall_s": 0.001,
               **shape}
        assert policy.decide(rep) == "full", shape


def test_tail_retention_gates_the_report_ring(monkeypatch):
    """finish() integration: with KAO_TRACE_TAIL armed, dropped
    fast-clean traces never reach /debug/solves' ring, head/full ones
    do (stamped with their decision), and the counters account for
    every finish."""
    tail = otrace.TAIL
    snap_before = tail.snapshot()
    tail.configure("head=4,window=64,quantile=0.95,min=8")
    try:
        seen = {"full": [], "head": [], "dropped": []}
        for i in range(60):
            tr = otrace.begin(True, name="tailprobe")
            rep = otrace.finish(tr)
            seen[rep["retention"]].append(rep["trace_id"])
        # a degraded trace is always retained in full
        tr = otrace.begin(True, name="tailprobe")
        otrace.mark("degrade", rung="pallas_to_xla")
        rep = otrace.finish(tr)
        assert rep["retention"] == "full"
        assert otrace.RECENT.get(rep["trace_id"]) is not None
        assert seen["dropped"], "head=4 over 60 traces must drop some"
        for tid in seen["dropped"]:
            assert otrace.RECENT.get(tid) is None, tid
        for tid in seen["head"]:
            assert otrace.RECENT.get(tid) is not None, tid
        counts = tail.snapshot()["decisions"]
        for k in ("full", "head", "dropped"):
            assert counts[k] >= len(seen[k])
    finally:
        tail.configure("off" if not snap_before["enabled"] else "1")


# --------------------------------------------------------------------------
# the router+2-worker join (the ISSUE 15 acceptance shape)
# --------------------------------------------------------------------------


class _TracingWorker:
    """A scripted serve-worker stand-in that honors the causal-tracing
    contract: it extracts the router's traceparent, answers /submit
    with the adopted trace id, and serves the remote-parented span
    tree back on GET /debug/solves/<id> — from its OWN store, so two
    instances model two processes even in-process."""

    def __init__(self, warm=(), solve_s=0.0):
        self.warm = [list(k) for k in warm]
        self.solve_s = solve_s
        self.reports: dict = {}
        self.traceparents: list = []
        self._lock = threading.Lock()
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _json(self, status, obj):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/healthz"):
                    self._json(200, {
                        "status": "ok",
                        "cache": {"warm_buckets": fake.warm},
                        "queue": {"depth": 0},
                    })
                elif self.path.startswith("/debug/solves/"):
                    tid = self.path.rsplit("/", 1)[1].split("?")[0]
                    with fake._lock:
                        rep = fake.reports.get(tid)
                    if rep is None:
                        self._json(404, {"error": "no such trace"})
                    else:
                        self._json(200, rep)
                else:
                    self._json(404, {"error": "nope"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                tp = self.headers.get("traceparent")
                ctx = otrace.extract(tp)
                with fake._lock:
                    fake.traceparents.append(tp)
                if fake.solve_s:
                    time.sleep(fake.solve_s)
                wall = fake.solve_s or 0.01
                if ctx is not None:
                    with fake._lock:
                        fake.reports[ctx.trace_id] = {
                            "trace_id": ctx.trace_id,
                            "name": "request",
                            "started_unix": round(time.time(), 3),
                            "wall_s": wall,
                            "phases": {"ladder": wall / 2},
                            "spans": {
                                "name": "request",
                                "start_s": 0.0,
                                "wall_s": wall,
                                "attrs": {
                                    "parent_span_id": ctx.span_id,
                                    "span_kind": "server",
                                },
                                "spans": [{
                                    "name": "ladder",
                                    "start_s": 0.001,
                                    "wall_s": wall / 2,
                                }],
                            },
                        }
                self._json(200, {
                    "worker": fake.url,
                    "report": {"feasible": True},
                    **({"trace_id": ctx.trace_id} if ctx else {}),
                })

        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.url = f"http://127.0.0.1:{self.srv.server_address[1]}"
        threading.Thread(target=self.srv.serve_forever,
                         daemon=True).start()

    def kill(self):
        self.srv.shutdown()
        self.srv.server_close()


DEMO_PAYLOAD = {
    "assignment": demo_assignment().to_dict(),
    "brokers": "0-18",
    "topology": "even-odd",
    "solver": "tpu",
}
DEMO_KEY = affinity.bucket_key_of(DEMO_PAYLOAD)


def _router_over(workers, **kw):
    tracker = FleetTracker([w.url for w in workers], interval_s=3600,
                           timeout_s=2.0)
    tracker.poll_once()
    router = Router(tracker, lock_wait_s=kw.pop("lock_wait_s", 5.0),
                    solve_timeout_s=10.0, connect_timeout_s=2.0, **kw)
    srv = make_router_server("127.0.0.1", 0, router)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return router, srv, f"http://127.0.0.1:{srv.server_address[1]}"


def _get_json(url, timeout=15.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_router_hedged_request_yields_one_merged_multiprocess_trace():
    """The ISSUE 15 acceptance shape, against the REAL Router: one
    deadline-carrying /submit hedges onto a second worker, and
    GET /debug/traces/<id> returns the router's route-decision spans
    with BOTH workers' solve trees (primary + hedge duplicate)
    attached under their exact attempt spans — plus a single
    multi-process Perfetto export."""
    slow = _TracingWorker(warm=[DEMO_KEY], solve_s=1.2)
    fast = _TracingWorker()
    router, srv, url = _router_over([slow, fast], hedge_ms=100.0)
    try:
        payload = dict(DEMO_PAYLOAD, deadline_s=30.0)
        req = urllib.request.Request(
            f"{url}/submit", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=15) as resp:
            echoed = resp.headers.get("traceparent")
            body = json.loads(resp.read())
        # hedge attribution in the envelope (ISSUE 15 satellite): the
        # answering worker plus BOTH attempt span ids
        route = body["route"]
        assert route["worker"] == fast.url
        assert route["hedge_won"] is True
        assert route["answered_by_hedge"] is True
        assert route["primary_span_id"] != route["hedge_span_id"]
        tid = route["trace_id"]
        assert tid
        # the context was echoed AND propagated to both workers with
        # the SAME trace id
        assert otrace.extract(echoed).trace_id == tid
        for w in (slow, fast):
            assert len(w.traceparents) == 1
            assert otrace.extract(w.traceparents[0]).trace_id == tid
        # wait for the hedge LOSER to finish its solve and register
        deadline = time.time() + 10
        while time.time() < deadline and tid not in slow.reports:
            time.sleep(0.05)
        assert tid in slow.reports
        status, merged = _get_json(f"{url}/debug/traces/{tid}")
        assert status == 200
        # one root (the router), two remote processes under it
        assert merged["root"] is not None
        assert merged["root"]["trace_id"] == tid
        root_attrs = merged["root"]["spans"]["attrs"]
        assert root_attrs.get("hedged") is True
        assert root_attrs.get("hedge_won") is True
        span_names = _names(merged["root"]["spans"])
        assert "route_decision" in span_names
        assert "attempt" in span_names
        assert "hedge_launch" in span_names
        # the echoed traceparent's parent span must EXIST in the
        # stored tree (the root's ID is minted before the report
        # snapshot, not lazily after)
        assert merged["root"]["spans"]["span_id"] == \
            otrace.extract(echoed).span_id
        assert len(merged["processes"]) == 2
        assert merged["processes_total"] == 3
        attached = {p["attached_to"] for p in merged["processes"]}
        assert attached == {route["primary_span_id"],
                            route["hedge_span_id"]}
        procs = {p["process"] for p in merged["processes"]}
        assert procs == {slow.url, fast.url}
        # the chrome export is ONE file with per-process track groups
        status, chrome = _get_json(
            f"{url}/debug/traces/{tid}?format=chrome")
        assert status == 200
        pids = {e["pid"] for e in chrome["traceEvents"]}
        assert pids == {1, 2, 3}
        names_by_pid = {
            e["pid"]: e["args"]["name"]
            for e in chrome["traceEvents"]
            if e.get("name") == "process_name"
        }
        assert names_by_pid[1] == "kao router"
        assert {names_by_pid[2], names_by_pid[3]} == \
            {f"kao {slow.url}", f"kao {fast.url}"}
        ts = [e["ts"] for e in chrome["traceEvents"]
              if e.get("ph") != "M"]
        assert ts == sorted(ts)
        # unknown ids are a structured 404
        status, _ = _get_json(f"{url}/debug/traces/nosuchtrace")
        assert status == 404
    finally:
        srv.shutdown()
        srv.server_close()
        slow.kill()
        fast.kill()


def _names(span, acc=None):
    acc = [] if acc is None else acc
    acc.append(span["name"])
    for c in span.get("spans", []):
        _names(c, acc)
    return acc


def test_router_adopts_client_traceparent_end_to_end():
    """A client carrying its own traceparent owns the trace ID through
    router AND worker: the router's root is remote-parented, and the
    worker sees the same ID the client chose."""
    w = _TracingWorker(warm=[DEMO_KEY])
    router, srv, url = _router_over([w])
    try:
        client_tid = "c11e207f00d5c0de"
        header = otrace.inject(client_tid, "abcdef0123456789")
        req = urllib.request.Request(
            f"{url}/submit", data=json.dumps(DEMO_PAYLOAD).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": header},
        )
        with urllib.request.urlopen(req, timeout=15) as resp:
            body = json.loads(resp.read())
        assert body["route"]["trace_id"] == client_tid
        assert otrace.extract(w.traceparents[0]).trace_id == client_tid
        rep = otrace.RECENT.get(client_tid)
        assert rep is not None
        assert rep["spans"]["attrs"]["parent_span_id"] == \
            "abcdef0123456789"
    finally:
        srv.shutdown()
        srv.server_close()
        w.kill()


def test_merge_fleet_trace_degrades_without_router_half():
    """The ring evicted the router's report: the worker trees still
    union side by side (attached_to None), and the chrome export still
    renders one pid per process."""
    rep = {
        "trace_id": "aa", "name": "request", "started_unix": 1.0,
        "wall_s": 0.5,
        "spans": {"name": "request", "start_s": 0.0, "wall_s": 0.5,
                  "attrs": {"parent_span_id": "deadbeefdeadbeef"}},
    }
    merged = ocausal.merge_fleet_trace(
        "aa", None, [{"process": "http://w1", "report": rep}])
    assert merged["root"] is None
    assert merged["processes"][0]["attached_to"] is None
    assert merged["processes_total"] == 1
    chrome = ochrome.to_chrome_fleet(merged)
    assert {e["pid"] for e in chrome["traceEvents"]} == {1}


def test_collect_remote_tolerates_dead_and_missing_workers():
    rep = {"trace_id": "bb", "name": "request",
           "spans": {"name": "request"}}

    def fetch(url, tid):
        if url == "http://dead":
            raise OSError("connection refused")
        if url == "http://misses":
            return None
        return rep

    reports, errors = ocausal.collect_remote(
        ["http://w1", "http://dead", "http://misses"], "bb",
        fetch=fetch)
    assert [r["process"] for r in reports] == ["http://w1"]
    assert list(errors) == ["http://dead"]
