"""Prometheus text-format validator for ``serve.render_metrics`` —
the guard for every future ``kao_*`` addition (ISSUE 3 satellite).

Regex-level checks, per the Prometheus exposition format:

- every comment line is a well-formed ``# HELP`` / ``# TYPE``;
- every sample family has a HELP **and** TYPE pair (histogram
  ``_bucket``/``_sum``/``_count`` samples resolve to their base
  family);
- metric and label names are legal; label values are quoted strings;
- sample values parse as floats;
- no duplicate samples (same name + same label set).
"""

import re

from kafka_assignment_optimizer_tpu import serve as srv
from kafka_assignment_optimizer_tpu.obs import trace as otrace

_COMMENT = re.compile(
    r"^# (HELP|TYPE) ([a-zA-Z_:][a-zA-Z0-9_:]*) (.+)$"
)
_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(.*)\})?"
    r" (-?(?:[0-9]+(?:\.[0-9]+)?|\.[0-9]+)(?:[eE][-+]?[0-9]+)?"
    r"|NaN|[+-]Inf)$"
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}
_HISTO_SUFFIXES = ("_bucket", "_sum", "_count")


def validate_prometheus(text: str):
    """Parse ``text``; returns the set of (name, labels) samples seen.
    Raises AssertionError with the offending line on any violation."""
    helps: dict[str, str] = {}
    types: dict[str, str] = {}
    samples: set = set()
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if line == "":
            continue
        if line.startswith("#"):
            m = _COMMENT.match(line)
            assert m, f"malformed comment line: {line!r}"
            kind, name, rest = m.groups()
            if kind == "TYPE":
                assert rest in _TYPES, f"bad TYPE {rest!r}: {line!r}"
                assert name not in types, f"duplicate TYPE for {name}"
                types[name] = rest
            else:
                assert name not in helps, f"duplicate HELP for {name}"
                helps[name] = rest
            continue
        m = _SAMPLE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, labels, value = m.groups()
        float(value.replace("Inf", "inf"))  # parses
        canon = ()
        if labels:
            pairs = _LABEL.findall(labels)
            # the label regex must account for the whole labels blob
            rebuilt = ",".join(f'{k}="{v}"' for k, v in pairs)
            assert rebuilt == labels, f"bad labels in: {line!r}"
            assert len({k for k, _ in pairs}) == len(pairs), (
                f"duplicate label name: {line!r}"
            )
            canon = tuple(sorted(pairs))
        key = (name, canon)
        assert key not in samples, f"duplicate sample: {line!r}"
        samples.add(key)
        # resolve histogram/summary series to their base family
        base = name
        for suf in _HISTO_SUFFIXES:
            stem = name[: -len(suf)] if name.endswith(suf) else None
            if stem and types.get(stem) in ("histogram", "summary"):
                base = stem
                break
        assert base in types, f"sample without # TYPE: {line!r}"
        assert base in helps, f"sample without # HELP: {line!r}"
    return samples


def test_render_metrics_is_valid_prometheus():
    # move some counters + a batch + a phase observation first, so the
    # labeled families and the histogram render non-empty
    srv._count(requests_total=1)
    srv._record_batch(3, 0.01, [
        {"feasible": True, "replica_moves": 1, "objective_weight": 5},
    ])
    otrace.observe_phase("ladder", 0.2)
    text = srv.render_metrics()
    samples = validate_prometheus(text)
    names = {n for n, _ in samples}
    assert "kao_requests_total" in names
    assert "kao_solves_total" in names
    assert ("kao_batch_size_total", (("size", "3"),)) in samples
    assert "kao_phase_seconds_bucket" in names
    assert "kao_phase_seconds_sum" in names
    assert "kao_phase_seconds_count" in names


def test_phase_histogram_is_cumulative_with_inf_terminal():
    otrace.observe_phase("_fmt_probe", 0.001)
    otrace.observe_phase("_fmt_probe", 999.0)  # beyond the last bucket
    text = srv.render_metrics()
    rows = {}
    for line in text.splitlines():
        m = _SAMPLE.match(line)
        if not m or m.group(1) != "kao_phase_seconds_bucket":
            continue
        labels = dict(_LABEL.findall(m.group(2)))
        if labels.get("phase") == "_fmt_probe":
            rows[labels["le"]] = float(m.group(3))
    count = next(
        float(_SAMPLE.match(ln).group(3))
        for ln in text.splitlines()
        if ln.startswith('kao_phase_seconds_count{phase="_fmt_probe"}')
    )
    les = [le for le in rows if le != "+Inf"]
    # cumulative: monotone non-decreasing in le, +Inf equals count
    ordered = sorted(les, key=float)
    vals = [rows[le] for le in ordered]
    assert vals == sorted(vals)
    assert rows["+Inf"] == count == 2.0
    # the 999 s observation only appears in the +Inf bucket
    assert vals[-1] == 1.0


def test_build_info_and_uptime_on_metrics():
    """ISSUE 9 satellite: the build-identity gauge and process uptime
    are always present, well-formed, and carry the full label set."""
    text = srv.render_metrics()
    samples = validate_prometheus(text)
    names = {n for n, _ in samples}
    assert "kao_build_info" in names
    assert "kao_uptime_seconds" in names
    info = next(labels for n, labels in samples if n == "kao_build_info")
    assert {k for k, _ in info} == {"version", "jax", "backend",
                                   "devices"}
    uptime = next(
        float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
        if ln.startswith("kao_uptime_seconds ")
    )
    assert uptime >= 0.0


def test_solve_seconds_histogram_and_exemplars_render():
    """kao_solve_seconds{class=} + its exemplar sidecar family pass the
    exposition validator and agree with the flight-record stream."""
    from kafka_assignment_optimizer_tpu.obs import flight as oflight

    # reset: an earlier test's solve may hold this bucket's exemplar
    # (worst-recent wins), which would make this assertion order-fragile
    oflight.reset_solve_stats()
    oflight.observe_solve("solve", 0.7, trace_id="fmtprobe01")
    text = srv.render_metrics()
    samples = validate_prometheus(text)
    names = {n for n, _ in samples}
    assert {"kao_solve_seconds_bucket", "kao_solve_seconds_sum",
            "kao_solve_seconds_count",
            "kao_solve_seconds_exemplar"} <= names
    assert any(
        n == "kao_solve_seconds_exemplar"
        and ("trace_id", "fmtprobe01") in labels
        for n, labels in samples
    )
    # SLO families render with HELP/TYPE for every class
    assert "kao_slo_burn_rate" in names
    assert "kao_slo_events_total" in names


def test_rollout_families_predeclared_at_zero():
    """ISSUE 12 satellite: every kao_rollout_* family renders (at
    zero) before the first rollout ever runs, with HELP/TYPE pairs —
    dashboards can alert on rate() from day one."""
    text = srv.render_metrics()
    samples = validate_prometheus(text)
    names = {n for n, _ in samples}
    for k in srv._ROLLOUT_COUNTER_NAMES:
        assert f"kao_rollout_{k}" in names, k
    assert "kao_rollout_active" in names  # the gauge rides along


def test_decompose_families_predeclared_at_zero():
    """PR 16 satellite: the kao_decompose_* families render (at zero)
    before the first decomposed solve ever runs, every kind label
    pre-declared, with HELP/TYPE pairs — same contract as rollout."""
    from kafka_assignment_optimizer_tpu.decompose.stats import (
        COUNTER_NAMES,
    )

    text = srv.render_metrics()
    samples = validate_prometheus(text)
    names = {n for n, _ in samples}
    assert "kao_decompose_total" in names
    assert "kao_decompose_last_bound_gap" in names
    assert "kao_decompose_last_subproblems" in names
    kinds = {dict(lbl).get("kind") for n, lbl in samples
             if n == "kao_decompose_total"}
    for k in COUNTER_NAMES:
        assert k in kinds, (k, kinds)


def test_mesh_families_predeclared_at_zero():
    """ISSUE 19 satellite: the kao_mesh_* families render before the
    first sharded solve ever runs — the counters at zero, the axis
    gauges as soon as a mesh exists — with HELP/TYPE pairs, and the
    per-bucket choice gauge appears once evidence lands."""
    from kafka_assignment_optimizer_tpu.parallel import mesh as pm

    pm.reset_mesh_adapt()
    try:
        text = srv.render_metrics()
        samples = validate_prometheus(text)
        names = {n for n, _ in samples}
        assert "kao_mesh_sharding_search_evals_total" in names
        assert "kao_mesh_reshard_bytes_total" in names
        zero = {(n, lbl) for n, lbl in samples
                if n == "kao_mesh_sharding_search_evals_total"}
        assert zero  # pre-declared, value row present at zero
        # once evidence lands, the bucket's choice is a labeled gauge;
        # build the 8-device mesh first (the chooser resolves against
        # the live axis sizes) and qualify BOTH sides so the rendered
        # choice is the never-guess rule's verdict, not sample order
        pm.make_mesh(8)
        bkt = (32, 8, 90, 3)
        for _ in range(pm.MESH_MIN_SOLVES):
            pm.note_sharding_evidence(bkt, (8, 1), lanes=4, solves=1,
                                      device_s=2.0)
            pm.note_sharding_evidence(bkt, (4, 2), lanes=4, solves=1,
                                      device_s=0.5)
        samples = validate_prometheus(srv.render_metrics())
        rows = [dict(lbl) for n, lbl in samples
                if n == "kao_mesh_bucket_sharding"]
        assert any(r.get("spec") == "4x2" for r in rows), rows
    finally:
        pm.reset_mesh_adapt()


def test_healthz_mesh_section_shape():
    """ISSUE 19 satellite: the /healthz mesh section carries the axis
    sizes, sharding mode, per-bucket evidence, counters, and the
    MEMOIZED multi-process probe verdict (never probed inline —
    /healthz must stay cheap), off the same snapshot the kao_mesh_*
    families render from."""
    from kafka_assignment_optimizer_tpu.parallel import mesh as pm

    pm.reset_mesh_adapt()
    try:
        pm.note_sharding_evidence((32, 8, 90, 3), (4, 2), lanes=4,
                                  solves=2, device_s=1.0)
        hz = srv._healthz_mesh()
        assert hz["sharding_mode"] in ("auto", "spec", "off")
        assert hz["min_solves"] == pm.MESH_MIN_SOLVES
        assert set(hz["counters"]) == {"search_evals", "reshard_bytes"}
        (row,) = hz["buckets"].values()
        assert row["evidence"]["4x2"]["solves"] == 2
        assert "chosen" in row
        procs = hz["processes"]
        assert procs["n_processes"] >= 1
        assert "multiprocess_probe" in procs
        assert isinstance(procs["multiprocess_probe"]["probed"], bool)
    finally:
        pm.reset_mesh_adapt()


def test_metrics_http_content_type():
    """ISSUE 9 satellite: /metrics serves the Prometheus text
    exposition content type (version 0.0.4) over real HTTP."""
    import threading
    import urllib.request

    from kafka_assignment_optimizer_tpu.serve import make_server

    s = make_server(port=0)
    t = threading.Thread(target=s.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{s.server_address[1]}/metrics"
        with urllib.request.urlopen(url, timeout=30) as resp:
            ctype = resp.headers.get("Content-Type")
            body = resp.read().decode()
    finally:
        s.shutdown()
        s.server_close()
    assert ctype == "text/plain; version=0.0.4"
    validate_prometheus(body)


def test_debug_endpoints_declare_json_content_type():
    """ISSUE 13 satellite: /debug/slo and /debug/solves/<id>
    (?format=chrome included) declare Content-Type: application/json
    over real HTTP, alongside the /metrics text-exposition check
    above — a JSON body served as text/plain breaks strict clients."""
    import json
    import threading
    import urllib.request

    from kafka_assignment_optimizer_tpu.serve import make_server

    # a retrievable solve report for the /debug/solves leg
    tr = otrace.begin(True, name="ctype_probe")
    with otrace.span("bounds"):
        pass
    rep = otrace.finish(tr)
    tid = rep["trace_id"]
    s = make_server(port=0)
    t = threading.Thread(target=s.serve_forever, daemon=True)
    t.start()
    try:
        base = f"http://127.0.0.1:{s.server_address[1]}"
        for path in ("/debug/slo", f"/debug/solves/{tid}",
                     f"/debug/solves/{tid}?format=chrome"):
            with urllib.request.urlopen(base + path,
                                        timeout=30) as resp:
                assert resp.headers.get("Content-Type") == \
                    "application/json", path
                body = json.loads(resp.read())  # parses as JSON
        # the chrome response is trace-event JSON, not a solve report
        assert "traceEvents" in body, list(body)
    finally:
        s.shutdown()
        s.server_close()


def test_router_families_are_valid_exposition():
    """ISSUE 14 satellite: the kao-router's kao_router_* families —
    rendered through the shared obs.expo helpers — pass the same
    validator as every serve surface, with every family pre-declared
    (HELP/TYPE) even before the first proxied request."""
    from kafka_assignment_optimizer_tpu.fleet.health import FleetTracker
    from kafka_assignment_optimizer_tpu.fleet.router import (
        Router,
        render_router_metrics,
    )

    tracker = FleetTracker(
        ["http://w1:1", "http://w2:2"], interval_s=3600,
        fetch=lambda u: {"cache": {"warm_buckets": [[19, 2, 32, 3]]}},
    )
    tracker.poll_once()
    router = Router(tracker)
    # counters move so the labeled families render non-empty rows
    router._count("requests_total", "submit")
    router._count("affinity_hits_total")
    router._count("retries_total", "shed")
    text = render_router_metrics(router)
    samples = validate_prometheus(text)
    names = {n for n, _ in samples}
    for fam in ("kao_router_requests_total",
                "kao_router_affinity_hits_total",
                "kao_router_affinity_misses_total",
                "kao_router_affinity_rate",
                "kao_router_retries_total",
                "kao_router_hedges_total",
                "kao_router_hedge_wins_total",
                "kao_router_sticky_total",
                "kao_router_exhausted_total",
                "kao_router_workers",
                "kao_router_worker_up",
                "kao_router_worker_warm_buckets"):
        assert fam in names, fam
    assert ("kao_router_worker_up",
            (("worker", "http://w1:1"),)) in samples


def test_trace_families_on_both_surfaces():
    """ISSUE 15 satellite: the ``kao_trace_*`` families (tail-based
    retention decisions + W3C traceparent codec traffic) render
    through the shared ``obs.trace.trace_families`` helper on BOTH
    exposition surfaces — serve's ``/metrics`` and the kao-router's —
    with HELP/TYPE pairs, every decision/event label pre-declared at
    zero, and values that track the counters."""
    from kafka_assignment_optimizer_tpu.fleet.health import FleetTracker
    from kafka_assignment_optimizer_tpu.fleet.router import (
        Router,
        render_router_metrics,
    )

    # move the codec counters so the values are provably live
    otrace.extract("garbage-header")               # malformed += 1
    ctx = otrace.extract(otrace.inject("ab" * 8))  # injected/extracted
    assert ctx is not None
    malformed = otrace.PROPAGATION["malformed"]

    tracker = FleetTracker(["http://w1:1"], interval_s=3600,
                           fetch=lambda u: {"cache": {}})
    tracker.poll_once()
    for text in (srv.render_metrics(),
                 render_router_metrics(Router(tracker))):
        samples = validate_prometheus(text)
        by_key = {
            (n, lab): True for n, lab in samples
        }
        names = {n for n, _ in samples}
        assert "kao_trace_tail_enabled" in names
        assert "kao_router_trace_reports" in names \
            or "kao_phase_seconds_count" in names  # surface-specific
        for decision in ("full", "head", "dropped"):
            assert ("kao_trace_retained_total",
                    (("decision", decision),)) in by_key, decision
        for event in ("extracted", "malformed", "injected"):
            assert ("kao_trace_context_total",
                    (("event", event),)) in by_key, event
        # the rendered malformed count matches the live counter
        row = re.search(
            r'^kao_trace_context_total\{event="malformed"\} (\d+)$',
            text, re.M)
        assert row and int(row.group(1)) >= malformed


def test_validator_rejects_malformed_exposition():
    import pytest

    for bad in (
        "kao_x 1\n",                                  # no HELP/TYPE
        "# TYPE kao_y counter\nkao_y 1\n",            # no HELP
        "# HELP kao_z z\n# TYPE kao_z counter\nkao_z one\n",  # bad value
        "# HELP kao_w w\n# TYPE kao_w counter\n"
        "kao_w 1\nkao_w 2\n",                         # duplicate sample
        "# HELP kao_v v\n# TYPE kao_v wrongtype\nkao_v 1\n",
        '# HELP kao_u u\n# TYPE kao_u counter\n'
        'kao_u{9bad="x"} 1\n',                        # bad label name
    ):
        with pytest.raises(AssertionError):
            validate_prometheus(bad)
