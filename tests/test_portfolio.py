"""Portfolio lanes (the PR-11 tentpole, docs/PORTFOLIO.md).

Pins the contracts the portfolio dispatcher rests on:

- config is DATA: a 1-lane portfolio carrying the default config is
  bit-identical to the solo sweep solve (and a lane's trajectory does
  not depend on how many other lanes race beside it), so one
  lane-padded executable per bucket serves every config and width;
- first-to-certify early exit is deterministic: under a forced
  mid-ladder certificate the solve retires the ladder at the same
  boundary with the same plan and the same winner-lane provenance on
  every run;
- the compound 2-move exchange accepts exactly the pair-atomic moves
  it should (and nothing on config-disabled lanes), keeps every hard
  invariant, and its carried-histogram deltas replay a from-scratch
  rebuild bit-for-bit — through the XLA and Pallas-interpret scorer
  bundles alike;
- same-bucket portfolio solves share executables: the second solve
  compiles nothing.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance
from kafka_assignment_optimizer_tpu.parallel import mesh as pm
from kafka_assignment_optimizer_tpu.solvers.tpu import arrays, bucket
from kafka_assignment_optimizer_tpu.solvers.tpu.engine import solve_tpu
from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed
from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
    _compound_sweep_delta,
    _histograms,
    make_sweep_solver_fn,
    propose_compound,
)
from kafka_assignment_optimizer_tpu.utils import gen


def _adv_instance(seed: int, **overrides):
    kw = dict(n_brokers=32, n_topics_low=3, n_topics_high=3,
              parts_per_topic=10, seed=seed)
    kw.update(overrides)
    sc = gen.adversarial(**kw)
    return build_instance(sc.current, sc.broker_list, sc.topology)


def _messy_instance(seed: int):
    current, brokers, topo, target_rf = gen.messy_case(seed)
    return build_instance(current, brokers, topo, target_rf)


# ------------------------------------------------------------- parity


def test_default_config_lane_bit_identical_to_solo():
    """A 1-lane portfolio with the default config replays the solo
    sweep solve bit-for-bit — per-lane config arrays change nothing
    until a config actually differs."""
    inst = _adv_instance(7)
    m = arrays.from_instance(inst)
    seed = np.asarray(greedy_seed(inst), np.int32)
    mesh = pm.make_mesh()
    key = jax.random.PRNGKey(0)
    temps = arrays.geometric_temps(2.0, 0.02, 16)

    state = pm.init_sweep_state(m, jnp.asarray(seed), key, mesh, 2)
    _st, ba1, bk1, cv1 = pm.solve_on_mesh(
        m, None, None, mesh, 2, 16, 1, engine="sweep", temps=temps,
        state=state,
    )
    stacked = arrays.stack_models(
        [arrays.with_config(m, arrays.DEFAULT_CONFIG)]
    )
    _st2, ba2, bk2, cv2 = pm.solve_lanes(
        stacked, mesh, 2, temps, lane_seeds=seed[None],
        keys=jnp.stack([key]), engine="sweep",
    )
    np.testing.assert_array_equal(np.asarray(ba1), np.asarray(ba2)[:, 0])
    np.testing.assert_array_equal(np.asarray(bk1), np.asarray(bk2)[:, 0])
    np.testing.assert_array_equal(np.asarray(cv1), np.asarray(cv2)[:, 0])


def test_lane_trajectories_independent_of_portfolio_width():
    """Lane i's best plan is bit-identical whether 2 or 4 lanes race —
    the vmap is element-wise and lane keys derive from the lane index,
    never the width — which is what makes 'bit-identical winning plans
    across portfolio widths' hold whenever the same lane wins."""
    inst = _adv_instance(7)
    m = arrays.from_instance(inst)
    seed = np.asarray(greedy_seed(inst), np.int32)
    mesh = pm.make_mesh()
    key = jax.random.PRNGKey(3)
    temps = arrays.geometric_temps(2.0, 0.02, 8)
    cfgs = arrays.portfolio_configs(4)

    outs = {}
    for width in (2, 4):
        stacked = arrays.stack_models(
            [arrays.with_config(m, c) for c in cfgs[:width]]
        )
        keys = jnp.stack(
            [key] + [jax.random.fold_in(key, i)
                     for i in range(1, width)]
        )
        lane_seeds = np.stack([seed] * width)
        outs[width] = pm.solve_lanes(
            stacked, mesh, 2, temps, lane_seeds=lane_seeds, keys=keys,
            engine="sweep",
        )
    for lane in range(2):
        np.testing.assert_array_equal(
            np.asarray(outs[2][1])[:, lane],
            np.asarray(outs[4][1])[:, lane],
        )
        np.testing.assert_array_equal(
            np.asarray(outs[2][2])[:, lane],
            np.asarray(outs[4][2])[:, lane],
        )


def test_portfolio_configs_table():
    cfgs = arrays.portfolio_configs(8)
    assert cfgs[0] == arrays.DEFAULT_CONFIG  # lane 0 anchors the solo config
    assert len({(c.lam, c.temp_scale, c.compound) for c in cfgs}) == 8
    # cycling past the table is defined (no default reaches it)
    assert arrays.portfolio_configs(10)[8] == cfgs[0]
    # provenance round-trip (stats / flight records)
    rt = arrays.model_config(
        arrays.with_config(arrays.from_instance(_adv_instance(7)),
                           cfgs[3])
    )
    assert rt == dataclasses.asdict(cfgs[3])


def test_adaptive_table_demotes_never_winners(monkeypatch):
    """ISSUE 12 satellite (the ROADMAP item 3 follow-on): with
    KAO_PORTFOLIO_ADAPT set and enough evidence, never-winning configs
    sink to the tail (and out of sub-table widths); with the gate off
    — the default — the table is PINNED to the static order
    regardless of banked evidence."""
    arrays.reset_portfolio_adapt()
    try:
        monkeypatch.delenv("KAO_PORTFOLIO_ADAPT", raising=False)
        for _ in range(arrays.ADAPT_MIN_SOLVES + 4):
            arrays.note_portfolio_result(arrays.PORTFOLIO_TABLE[5])
        # pinned-table default: evidence banked, order unchanged
        assert arrays.portfolio_configs(8) == list(
            arrays.PORTFOLIO_TABLE)
        snap = arrays.portfolio_adapt_snapshot()
        assert not snap["enabled"] and not snap["adapted"]
        assert snap["wins"][5] == arrays.ADAPT_MIN_SOLVES + 4
        # gate on: winners first, lane 0 still the default anchor
        monkeypatch.setenv("KAO_PORTFOLIO_ADAPT", "1")
        cfgs = arrays.portfolio_configs(8)
        assert cfgs[0] == arrays.DEFAULT_CONFIG
        assert cfgs[1] == arrays.PORTFOLIO_TABLE[5]
        # a width-2 portfolio now races the actual winner, not slot 1
        assert arrays.portfolio_configs(2)[1] \
            == arrays.PORTFOLIO_TABLE[5]
        snap = arrays.portfolio_adapt_snapshot()
        assert snap["adapted"] and snap["order"][1] == 5
        # below the evidence floor nothing reorders, even gated on
        arrays.reset_portfolio_adapt()
        arrays.note_portfolio_result(arrays.PORTFOLIO_TABLE[3])
        assert arrays.portfolio_configs(8) == list(
            arrays.PORTFOLIO_TABLE)
    finally:
        arrays.reset_portfolio_adapt()


# -------------------------------------------------- engine + early exit


@pytest.mark.soak
@pytest.mark.slow  # ~23 s; nightly. Tier-1 keeps the forced-midladder
# early-exit and disabled-lane portfolio pins; the messy[1] close also
# re-proves nightly via the soak fuzz tier.
def test_engine_portfolio_stats_and_quality():
    """The engine-level dispatcher: portfolio provenance lands in
    stats, and at equal budget the portfolio closes the messy exact-band
    case (gen.messy_case(1) — the instance that was the tier-1 xfail)
    that the single default config cannot."""
    inst = _messy_instance(1)
    single = solve_tpu(inst, seed=1, engine="sweep", batch=8, rounds=32,
                       portfolio=False)
    port = solve_tpu(inst, seed=1, engine="sweep", batch=8, rounds=32,
                     portfolio=True)
    assert "portfolio" not in single.stats
    p = port.stats["portfolio"]
    assert p["width"] >= 2
    assert p["lane_bucket"] >= p["width"]
    assert port.stats["feasible"]
    assert not single.stats["feasible"]  # the documented barrier
    assert p["winner_lane"] is not None
    assert p["winner_config"] == dataclasses.asdict(
        arrays.portfolio_configs(p["width"])[p["winner_lane"]]
    )


def test_forced_midladder_certificate_early_exit_deterministic():
    """A mid-ladder boundary certificate retires the portfolio ladder
    first-to-certify: deterministically the same plan, the same winner
    lane, and a recorded time-to-certificate, on every run."""
    results = []
    for _ in range(2):
        inst = _adv_instance(9)
        # force the boundary certificate: the move bound accepts any
        # candidate and the weight bound is already met, so the FIRST
        # feasible boundary winner certifies mid-ladder
        inst.move_lower_bound_exact = lambda: 10**9
        inst.weight_upper_bound = lambda tight=False: -1
        res = solve_tpu(inst, seed=0, engine="sweep", batch=8,
                        rounds=32, portfolio=True,
                        cert_min_savings_s=0.0)
        results.append(res)
    a, b = results
    assert a.stats["early_stopped"] and b.stats["early_stopped"]
    pa, pb = a.stats["portfolio"], b.stats["portfolio"]
    assert pa["early_exit"] and pb["early_exit"]
    assert pa["winner_lane"] == pb["winner_lane"]
    assert pa["winner_lane"] is not None
    assert pa.get("certified_at_s") is not None
    # the retired ladder ran fewer rounds than the full schedule
    assert a.stats["rounds_run"] < 32
    assert a.stats["rounds_run"] == b.stats["rounds_run"]
    np.testing.assert_array_equal(a.a, b.a)


def test_portfolio_shares_one_lane_executable_per_bucket():
    """Two same-bucket portfolio solves dispatch ONE lane-padded
    executable: the second compiles nothing (the exec-cache counters
    are the acceptance evidence — docs/PORTFOLIO.md)."""
    a = _adv_instance(11)
    b = _adv_instance(12)
    solve_tpu(a, seed=0, engine="sweep", batch=8, rounds=16,
              portfolio=True)
    before = bucket.STATS.snapshot()
    res = solve_tpu(b, seed=1, engine="sweep", batch=8, rounds=16,
                    portfolio=True)
    after = bucket.STATS.snapshot()
    assert res.stats["portfolio"]["width"] >= 2
    assert after["compiles_total"] == before["compiles_total"], (
        "a same-bucket portfolio solve recompiled the lane executable"
    )


# ------------------------------------------- compound 2-move exchange


def _compound_fixture(seed=0, chains=2):
    inst = _adv_instance(7)
    m = arrays.from_instance(inst)
    a = jnp.broadcast_to(
        jnp.asarray(greedy_seed(inst), jnp.int32),
        (chains, inst.num_parts, inst.max_rf),
    )
    _f, _r, cnt, lcnt, rcnt = _histograms(m, a)
    return inst, m, a, cnt, lcnt, rcnt


def test_compound_disabled_lane_declines_everything():
    """A lane whose config turns the compound move off rejects every
    proposal — the sweep itself still runs (one executable for every
    config), it just never moves."""
    inst, m, a, cnt, lcnt, rcnt = _compound_fixture()
    m_off = arrays.with_config(
        m, dataclasses.replace(arrays.DEFAULT_CONFIG, compound=False)
    )
    prop, _d, _lo = propose_compound(
        m_off, a, jax.random.PRNGKey(0), jnp.float32(5.0), cnt, lcnt,
        rcnt,
    )
    assert not bool(np.asarray(prop.prio > 0).any())
    a2, c2, l2, r2 = _compound_sweep_delta(
        m_off, a, cnt, lcnt, rcnt, jax.random.PRNGKey(0),
        jnp.float32(5.0),
    )
    np.testing.assert_array_equal(np.asarray(a2), np.asarray(a))


def test_compound_accepts_and_updates_histograms_exactly():
    """At high temperature legal compound proposals are accepted, the
    applied population keeps every hard invariant, and the carried
    histogram deltas are bit-identical to a from-scratch rebuild."""
    inst, m, a, cnt, lcnt, rcnt = _compound_fixture()
    moved = False
    accepted = False
    key = jax.random.PRNGKey(1)
    for _ in range(6):
        key, sub = jax.random.split(key)
        prop, _d, _lo = propose_compound(
            m, a, sub, jnp.float32(500.0), cnt, lcnt, rcnt
        )
        a2, cnt2, lcnt2, rcnt2 = _compound_sweep_delta(
            m, a, cnt, lcnt, rcnt, sub, jnp.float32(500.0)
        )
        accepted = accepted or bool(np.asarray(prop.prio > 0).any())
        if (np.asarray(a2) != np.asarray(a)).any():
            moved = True
        # carried counts == from-scratch rebuild of the applied state
        _f, _r, cr, lr, rr = _histograms(m, a2)
        np.testing.assert_array_equal(np.asarray(cnt2), np.asarray(cr))
        np.testing.assert_array_equal(np.asarray(lcnt2), np.asarray(lr))
        np.testing.assert_array_equal(np.asarray(rcnt2), np.asarray(rr))
        for n in range(a2.shape[0]):
            v = inst.violations(np.asarray(a2)[n])
            assert v["duplicate_in_partition"] == 0
            assert v["null_in_valid_slot"] == 0
            assert v["slot_out_of_range"] == 0
        a, cnt, lcnt, rcnt = a2, cnt2, lcnt2, rcnt2
    assert accepted  # accept coverage: proposals do get accepted
    assert moved  # ... and the move set actually moves state


def test_compound_low_temp_declines_penalized_pairs():
    """Freeze-out decline coverage: at near-zero temperature with the
    strict default lam, only delta >= 0 pairs survive — the applied
    population can never score worse than it started."""
    from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
        chain_scores,
    )

    inst, m, a, cnt, lcnt, rcnt = _compound_fixture()
    w0, p0 = (np.asarray(x) for x in chain_scores(m, a))
    a2, *_ = _compound_sweep_delta(
        m, a, cnt, lcnt, rcnt, jax.random.PRNGKey(2), jnp.float32(1e-6)
    )
    w2, p2 = (np.asarray(x) for x in chain_scores(m, a2))
    score0 = w0 - 64 * p0
    score2 = w2 - 64 * p2
    assert (score2 >= score0).all(), (score0, score2)


@pytest.mark.soak
@pytest.mark.slow  # ~19 s; nightly. Tier-1 keeps the sweep-level
# kernel parity (test_sweep_solver_pallas_scorer_bit_identical) and
# the sharded interpret parity (test_mesh_sharding.py).
def test_compound_schedule_xla_vs_pallas_interpret_bit_parity():
    """The full sweep schedule — site, exchange, and compound sweeps —
    through both scorer bundles yields byte-identical winners: the
    compound step is shared code, and the bundles' surrounding stages
    are pinned bit-compatible."""
    inst = _adv_instance(8)
    m = arrays.from_instance(inst)
    seed = jnp.asarray(greedy_seed(inst), jnp.int32)
    temps = arrays.geometric_temps(2.0, 0.02, 8)  # sweeps 3 and 7 compound
    outs = {}
    for scorer in ("xla", "pallas-interpret"):
        solve = jax.jit(make_sweep_solver_fn(n_chains=2, scorer=scorer))
        ba, bk, _cv = solve(m, seed, jax.random.PRNGKey(5), temps)
        outs[scorer] = (np.asarray(ba), int(bk))
    np.testing.assert_array_equal(outs["xla"][0],
                                  outs["pallas-interpret"][0])
    assert outs["xla"][1] == outs["pallas-interpret"][1]
