"""Donated-buffer smoke tests (ISSUE 4 satellite, docs/PIPELINE.md).

The sweep solvers donate their carried state (``parallel.mesh``
``donate_argnums``) so each ladder chunk updates the chain populations
in HBM in place. The runtime enforces the donation contract even on the
CPU test mesh — a donated array is deleted at dispatch and reuse raises
— which is exactly what makes these tests tier-1-safe TPU insurance:
any code path that touches a state after handing it to a dispatch fails
HERE, in CPU CI, not in the first TPU bench run.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_assignment_optimizer_tpu.models.cluster import (
    Assignment,
    PartitionAssignment,
    Topology,
)
from kafka_assignment_optimizer_tpu.models.instance import build_instance
from kafka_assignment_optimizer_tpu.parallel import mesh as pm
from kafka_assignment_optimizer_tpu.solvers.tpu import arrays
from kafka_assignment_optimizer_tpu.solvers.tpu.arrays import (
    geometric_temps,
)
from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed


def _instance(rng, n_brokers=10, n_parts=24, rf=3, n_racks=2):
    parts = [
        PartitionAssignment(
            "t", p, rng.choice(n_brokers, size=rf, replace=False).tolist()
        )
        for p in range(n_parts)
    ]
    topo = Topology(
        rack_of={b: f"r{b % n_racks}" for b in range(n_brokers)}
    )
    return build_instance(
        Assignment(partitions=parts), list(range(n_brokers - 1)), topo
    )


def test_sweep_state_is_donated_and_reuse_raises(rng):
    """The single-instance sweep solver consumes its state: after one
    dispatch the input buffers are gone (in-place HBM update — no
    per-chunk full-population reallocation), and feeding the same state
    to a second dispatch raises instead of silently reading freed
    memory. Continuing from the RETURNED state — the engine's usage
    pattern — works across chunks."""
    inst = _instance(rng)
    m = arrays.from_instance(inst)
    seed = jnp.asarray(np.asarray(greedy_seed(inst), np.int32))
    mesh = pm.make_mesh()
    temps = geometric_temps(2.0, 0.02, 16)
    state0 = pm.init_sweep_state(m, seed, jax.random.PRNGKey(0), mesh, 2)

    st1, pop_a, pop_k, _curve = pm.solve_on_mesh(
        m, None, None, mesh, 2, 16, 1, engine="sweep", temps=temps,
        state=state0,
    )
    jax.block_until_ready(pop_a)
    leaves0 = jax.tree_util.tree_leaves(state0)
    assert all(x.is_deleted() for x in leaves0), (
        "sweep state was not donated — per-chunk full-population "
        "reallocation is back"
    )
    # chunk 2 from the returned state: the engine's carried-state pattern
    st2, pop_a2, _pk2, _c2 = pm.solve_on_mesh(
        m, None, None, mesh, 2, 16, 1, engine="sweep", temps=temps,
        state=st1,
    )
    jax.block_until_ready(pop_a2)
    # every candidate is still a real plan for this instance
    best = arrays.unpad_candidate(np.asarray(pm.fetch_global(pop_a2))[0],
                                  inst)
    assert best.shape == (inst.num_parts, inst.max_rf)
    # reuse of a consumed state must raise loudly, never return garbage
    with pytest.raises(Exception, match="[Dd]elet|[Dd]onat"):
        out = pm.solve_on_mesh(
            m, None, None, mesh, 2, 16, 1, engine="sweep", temps=temps,
            state=st1,
        )
        jax.block_until_ready(out[1])


def test_lane_state_is_donated(rng):
    """Same contract for the batched lane solver: the [n_dev, L, ...]
    lane state is consumed per dispatch and threads through chunks."""
    insts = [_instance(rng), _instance(rng)]
    models = [arrays.from_instance(i) for i in insts]
    m_stack = arrays.stack_models(models)
    lane_seeds = np.stack([
        arrays.pad_candidate(
            np.asarray(greedy_seed(i), np.int32), mm
        )
        for i, mm in zip(insts, models)
    ])
    keys = jnp.stack([jax.random.PRNGKey(s) for s in (0, 1)])
    mesh = pm.make_mesh()
    temps = geometric_temps(2.0, 0.02, 16)
    state0 = pm.init_lane_state(m_stack, lane_seeds, keys, mesh, 2)

    st1, pa, _pk, _cv = pm.solve_lanes(
        m_stack, mesh, 2, temps, state=state0, engine="sweep",
    )
    jax.block_until_ready(pa)
    assert all(
        x.is_deleted() for x in jax.tree_util.tree_leaves(state0)
    )
    st2, pa2, _pk2, _cv2 = pm.solve_lanes(
        m_stack, mesh, 2, temps, state=st1, engine="sweep",
    )
    jax.block_until_ready(pa2)


def test_donated_ladder_is_bit_deterministic(rng):
    """Repeated identical donated ladders must be bit-identical.

    Regression pin: init_sweep_state once fed the SAME
    ``np.broadcast_to`` view as both the population and best-snapshot
    leaves; device_put may zero-copy a contiguous-compatible host view,
    so the two donated leaves could silently share one buffer — and the
    solver's in-place updates then corrupted the sibling leaf,
    alignment-dependently (identical solves returned different,
    lower-quality plans). The state leaves must be independent buffers
    and the whole donated chunk chain exactly reproducible."""
    inst = _instance(rng)
    m = arrays.from_instance(inst)
    seed = jnp.asarray(np.asarray(greedy_seed(inst), np.int32))
    mesh = pm.make_mesh()
    temps = geometric_temps(2.0, 0.02, 16)

    def run():
        state = pm.init_sweep_state(
            m, seed, jax.random.PRNGKey(7), mesh, 2
        )
        for _ in range(2):
            state, pa, pk, cv = pm.solve_on_mesh(
                m, None, None, mesh, 2, 16, 1, engine="sweep",
                temps=temps, state=state,
            )
        jax.block_until_ready(pa)
        return (np.asarray(pm.fetch_global(pa)).copy(),
                np.asarray(pm.fetch_global(cv)).copy())

    pa0, cv0 = run()
    for _ in range(2):
        pa_i, cv_i = run()
        assert np.array_equal(pa0, pa_i)
        assert np.array_equal(cv0, cv_i)


def test_chain_engine_args_not_donated(rng):
    """The chain engine has no carried state — its seed and keys are
    plain arguments the engine DOES reuse across chunks, so they must
    survive a dispatch untouched."""
    inst = _instance(rng)
    m = arrays.from_instance(inst)
    seed = jnp.asarray(np.asarray(greedy_seed(inst), np.int32))
    key = jax.random.PRNGKey(0)
    mesh = pm.make_mesh()
    ba, bk, _cv = pm.solve_on_mesh(
        m, seed, key, mesh, 2, 2, 50, engine="chain",
    )
    jax.block_until_ready(ba)
    assert not seed.is_deleted() and not key.is_deleted()
    # second dispatch with the same args (the engine's reseed pattern)
    ba2, _bk2, _cv2 = pm.solve_on_mesh(
        m, seed, key, mesh, 2, 2, 50, engine="chain",
    )
    jax.block_until_ready(ba2)


@pytest.mark.soak
@pytest.mark.slow  # ~12 s; nightly. Tier-1 keeps the direct donation
# pins (lane state donated, sweep-state reuse raises) that fail first
# if donation breaks.
def test_engine_end_to_end_through_donated_path(rng):
    """A chunked sweep solve through the full engine (4 chunks threading
    donated state, pipelined dispatch on) stays feasible and verified —
    the CI stand-in for the TPU ladder."""
    from kafka_assignment_optimizer_tpu.api import optimize

    rng2 = np.random.default_rng(1)
    parts = [
        PartitionAssignment(
            "t", p, rng2.choice(12, size=3, replace=False).tolist()
        )
        for p in range(48)
    ]
    topo = Topology(rack_of={b: f"r{b % 3}" for b in range(12)})
    res = optimize(
        Assignment(partitions=parts), list(range(11)), topo,
        solver="tpu", engine="sweep", batch=8, rounds=32, seed=0,
        time_limit_s=3600.0, precompile=True,
    )
    st = res.solve.stats
    assert st["feasible"] is True
    assert st["rounds_run"] == 32
    assert st["pipeline"] is True
