"""Symmetry-aggregated bounds + leader-aware construction — the
machinery that certifies the 50k-partition jumbo scenario (r3).

- ``ProblemInstance._kept_weight_agg``: the level-2 kept-replica bound
  on the partition-symmetry-aggregated model (exact for the LP; the
  integer mode is a valid, possibly tighter relaxation of the true
  MILP).
- ``native.mcmf``: the C++ min-cost max-flow kernel behind leader-aware
  plan completion.
- ``solvers.lp_round``: aggregated MILP -> disaggregation -> MCMF
  completion path used past the unaggregated-LP size limit.
"""

from __future__ import annotations

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu.api import optimize
from kafka_assignment_optimizer_tpu.models import instance as inst_mod
from kafka_assignment_optimizer_tpu.models.instance import build_instance
from kafka_assignment_optimizer_tpu.utils import gen


def _inst(name, smoke=True):
    kw = gen.SMOKE_KWARGS[name] if smoke else {}
    sc = gen.SCENARIOS[name](**kw)
    return sc, build_instance(
        sc.current, sc.broker_list, sc.topology, target_rf=sc.target_rf
    )


# ---------------------------------------------------------------- mcmf


def test_mcmf_known_answer():
    from kafka_assignment_optimizer_tpu.native import mcmf

    # 0->1(2,$0) 0->2(2,$0) 1->3(2,$1) 2->3(2,$0) 1->2(1,-$1):
    # max-flow 4 forces both 0->1 units through the $1 arc
    f, c, af = mcmf([0, 0, 1, 2, 1], [1, 2, 3, 3, 2],
                    [2, 2, 2, 2, 1], [0, 0, 1, 0, -1], 0, 3, 4)
    assert (f, c) == (4, 2)
    assert af.tolist() == [2, 2, 2, 2, 0]
    # disconnected sink
    f, c, _ = mcmf([0], [1], [3], [5], 0, 2, 3)
    assert f == 0


def test_mcmf_matches_scipy_maxflow(rng):
    """Flow value == scipy max-flow; conservation holds at every node.

    Random DAGs (arcs only low->high node id), matching the kernel's
    successive-shortest-paths contract: negative arc COSTS are legal,
    negative-cost CYCLES are not (the completion networks are
    DAG-layered, so cycles cannot arise in production)."""
    import scipy.sparse as sp
    from scipy.sparse.csgraph import maximum_flow

    from kafka_assignment_optimizer_tpu.native import mcmf

    for _ in range(30):
        n = int(rng.integers(4, 12))
        m = int(rng.integers(5, 30))
        src = rng.integers(0, n, m)
        dst = rng.integers(0, n, m)
        ok = src != dst
        src, dst = (np.minimum(src, dst)[ok], np.maximum(src, dst)[ok])
        cap = rng.integers(1, 9, src.size)
        cost = rng.integers(-3, 4, src.size)
        # coo->csr sums parallel-arc capacities, matching the kernel's
        # independent parallel arcs in total s-t capacity
        g = sp.coo_matrix((cap, (src, dst)), shape=(n, n)).tocsr()
        ref = maximum_flow(g.astype(np.int32), 0, n - 1).flow_value
        f, _c, af = mcmf(src, dst, cap, cost, 0, n - 1, n)
        assert f == ref
        net = np.zeros(n)
        np.add.at(net, src, -af)
        np.add.at(net, dst, af)
        assert net[0] == -f and net[n - 1] == f
        assert np.abs(net[1:n - 1]).max(initial=0) == 0
        assert np.all(af >= 0) and np.all(af <= cap)


def test_mcmf_cost_matches_lp_oracle(rng):
    """Total cost at max flow == the min-cost-flow LP optimum (scipy
    linprog oracle), on random DAGs with negative arc costs. Pins the
    r4 rewrite (SPFA-per-augmentation -> Dijkstra potentials + blocking
    flow): flow-value parity alone would not catch a cost-accounting or
    potential-fold bug."""
    import scipy.sparse as sp
    from scipy.optimize import linprog
    from scipy.sparse.csgraph import maximum_flow

    from kafka_assignment_optimizer_tpu.native import mcmf

    for _ in range(30):
        n = int(rng.integers(4, 12))
        m = int(rng.integers(5, 30))
        src = rng.integers(0, n, m)
        dst = rng.integers(0, n, m)
        ok = src != dst
        src, dst = (np.minimum(src, dst)[ok], np.maximum(src, dst)[ok])
        cap = rng.integers(1, 9, src.size)
        cost = rng.integers(-3, 4, src.size)
        g = sp.coo_matrix((cap, (src, dst)), shape=(n, n)).tocsr()
        ref_flow = maximum_flow(g.astype(np.int32), 0, n - 1).flow_value
        f, c, _af = mcmf(src, dst, cap, cost, 0, n - 1, n)
        assert f == ref_flow
        if f == 0:
            assert c == 0
            continue
        # LP: min cost.x s.t. node conservation with s/t exchanging
        # exactly ref_flow units, 0 <= x <= cap
        a_eq = np.zeros((n, src.size))
        for i, (u, v) in enumerate(zip(src, dst)):
            a_eq[u, i] -= 1
            a_eq[v, i] += 1
        b_eq = np.zeros(n)
        b_eq[0] = -float(ref_flow)
        b_eq[n - 1] = float(ref_flow)
        r = linprog(cost.astype(float), A_eq=a_eq, b_eq=b_eq,
                    bounds=list(zip(np.zeros(src.size), cap.astype(float))),
                    method="highs")
        assert r.status == 0
        assert c == round(r.fun), (c, r.fun)


def test_mcmf_cost_matches_lp_oracle_cyclic(rng):
    """General digraphs — cycles, parallel arcs, negative costs — with
    guaranteed no negative-cost cycle: costs are potential-shifted
    (c = w + phi[u] - phi[v], w >= 0, random phi), so every cycle's
    total reduces to its nonnegative w-sum. This drives the kernel's
    cycle machinery (blocking-flow dead-marking, onpath guard,
    zero-reduced-cost reverse-arc cycles) that the DAG-only oracle test
    above never reaches (ADVICE r4)."""
    import scipy.sparse as sp
    from scipy.optimize import linprog
    from scipy.sparse.csgraph import maximum_flow

    from kafka_assignment_optimizer_tpu.native import mcmf

    for _ in range(30):
        n = int(rng.integers(4, 12))
        m = int(rng.integers(6, 36))
        src = rng.integers(0, n, m)
        dst = rng.integers(0, n, m)
        ok = src != dst  # no self-loops; cycles/parallel arcs stay
        src, dst = src[ok], dst[ok]
        if src.size == 0:
            continue
        w = rng.integers(0, 5, src.size)
        phi = rng.integers(-4, 5, n)
        cost = w + phi[src] - phi[dst]
        cap = rng.integers(1, 9, src.size)
        # duplicate arcs collapse in the coo->csr max-flow reference:
        # sum the capacities the same way for the flow-value check
        g = sp.coo_matrix((cap, (src, dst)), shape=(n, n)).tocsr()
        ref_flow = maximum_flow(g.astype(np.int32), 0, n - 1).flow_value
        f, c, _af = mcmf(src, dst, cap, cost, 0, n - 1, n)
        assert f == ref_flow
        if f == 0:
            assert c == 0
            continue
        a_eq = np.zeros((n, src.size))
        for i, (u, v) in enumerate(zip(src, dst)):
            a_eq[u, i] -= 1
            a_eq[v, i] += 1
        b_eq = np.zeros(n)
        b_eq[0] = -float(ref_flow)
        b_eq[n - 1] = float(ref_flow)
        r = linprog(cost.astype(float), A_eq=a_eq, b_eq=b_eq,
                    bounds=list(zip(np.zeros(src.size),
                                    cap.astype(float))),
                    method="highs")
        assert r.status == 0
        assert c == round(r.fun), (c, r.fun)


def test_mcmf_rejects_negative_cycle():
    """A residual-reachable negative-cost cycle is outside the SSP
    contract: the kernel must detect it and raise (rc=-2), not spin
    until the process aborts (fuzz-found crash class)."""
    from kafka_assignment_optimizer_tpu.native import mcmf

    # 0 -> 1 -> 2 -> 1 ... cycle 1->2->1 has total cost -1
    with pytest.raises(RuntimeError):
        mcmf([0, 1, 2, 2], [1, 2, 1, 3], [1, 5, 5, 1],
             [0, -3, 2, 0], 0, 3, 4)


# ------------------------------------------------- aggregated bound


@pytest.mark.parametrize("name", list(gen.SCENARIOS))
def test_agg_bound_matches_unaggregated(name):
    """The aggregated LP bound equals the unaggregated level-2 LP up to
    its extra (valid) cuts — never looser, and still a true upper bound
    on the exact optimum."""
    sc, inst = _inst(name)
    unagg = inst._kept_weight_lp()
    agg = inst._kept_weight_agg()
    agg_milp = inst._kept_weight_agg(integer=True)
    assert agg is not None and unagg is not None
    assert agg <= unagg  # u<=z + leader-slot cuts can only tighten
    assert agg_milp <= agg  # integer aggregation tightens further
    if name == "jumbo":
        return  # the exact-MILP oracle is minutes at jumbo-smoke size
    ex = optimize(solver="milp", **sc.kwargs)
    assert ex.solve.optimal
    assert agg_milp >= ex.solve.objective  # soundness: valid relaxation


@pytest.mark.soak
def test_agg_bound_sound_on_random_clusters(rng):
    """Aggregated LP/MILP bounds never undercut the exact optimum on
    random lopsided clusters (certificate soundness)."""
    from kafka_assignment_optimizer_tpu.models.cluster import (
        Assignment,
        PartitionAssignment,
        Topology,
    )

    for trial in range(6):
        n_b = int(rng.integers(5, 12))
        n_racks = int(rng.integers(1, 4))
        n_p = int(rng.integers(4, 24))
        rf = int(rng.integers(1, min(4, n_b)))
        topo = Topology.from_dict(
            {str(b): f"r{b % n_racks}" for b in range(n_b)}
        )
        parts = [
            PartitionAssignment(
                topic="t", partition=p,
                replicas=rng.choice(n_b, size=rf, replace=False).tolist(),
            )
            for p in range(n_p)
        ]
        drop = int(rng.integers(0, n_b)) if rng.random() < 0.5 else None
        brokers = [b for b in range(n_b) if b != drop]
        kw = dict(current=Assignment(partitions=parts),
                  broker_list=brokers, topology=topo)
        inst = build_instance(kw["current"], kw["broker_list"], topo)
        ex = optimize(solver="milp", **kw)
        assert ex.solve.optimal
        for bound in (inst._kept_weight_agg(),
                      inst._kept_weight_agg(integer=True)):
            assert bound is not None
            assert bound >= ex.solve.objective, trial


def test_level3_in_ladder_monotone():
    """weight_upper_bound levels are monotone non-increasing through
    the new level-3 tier."""
    _, inst = _inst("jumbo")
    l0 = inst.weight_upper_bound(level=0)
    l1 = inst.weight_upper_bound(level=1)
    l2 = inst.weight_upper_bound(level=2)
    l3 = inst.weight_upper_bound(level=3)
    assert l0 >= l1 >= l2 >= l3


# ------------------------------------------- aggregated construction


@pytest.mark.parametrize("name", ["decommission", "scale_out", "jumbo"])
def test_agg_construct_path_feasible(name, monkeypatch):
    """Force the aggregated construct path (as used past the size
    threshold) on small instances: the disaggregated, MCMF-completed,
    reseated plan must be feasible and at least as good as the greedy
    seed."""
    from kafka_assignment_optimizer_tpu.solvers import lp_round
    from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed

    monkeypatch.setattr(inst_mod, "AGG_MEMBER_THRESHOLD", 0)
    sc, inst = _inst(name)
    plan = lp_round.construct(inst)
    if plan is None:
        pytest.skip(f"aggregated vertex not realizable on {name} smoke")
    assert inst.is_feasible(plan)
    seed = greedy_seed(inst)
    assert (
        inst.preservation_weight(plan) >= inst.preservation_weight(seed)
        or inst.move_count(plan) <= inst.move_count(seed)
    )


def test_symmetric_instance_constructs_without_annealing(monkeypatch):
    """The cold-start fast path (VERDICT r2 item 2): on a
    symmetry-collapsible instance (every generated benchmark scenario
    at scale; here the FULL 10k-partition headline, whose collapse only
    appears at scale) the engine's constructor race wins before any
    device ladder is built — zero rounds run, plan certified. This is
    what keeps a cold process under the 5 s headline budget. The
    no-signal annealer path is pinned by
    ``test_lp_round.test_no_signal_keeps_annealing_path`` (demo: 19
    distinct classes of 19 members, agg_effective False)."""
    from kafka_assignment_optimizer_tpu.solvers.tpu import engine

    # pin the constructor-vs-annealer race: the production 5 s wait is
    # a latency guard, not the property under test, and a loaded CI
    # host can lose it despite correct engine behavior
    monkeypatch.setattr(engine, "_CONSTRUCT_WAIT_S", 120.0)
    sc, inst = _inst("decommission", smoke=False)
    assert inst.agg_effective()
    r = optimize(solver="tpu", seed=0, **sc.kwargs)
    s = r.solve.stats
    assert s["constructed"]
    assert s["construct_path"] == "agg"  # artifact evidence field
    assert s["proved_optimal"]
    assert s["rounds_run"] == 0
    assert s["feasible"]


def test_agg_construct_rf_decrease(monkeypatch):
    """RF-shrink through the aggregated path: classes then have MORE
    members than the target rf, so the greedy realization must cap
    per-partition keeps at rf (the uncapped version tripped the
    rank >= rf guard and silently failed construction). Forced agg
    (threshold 0) + forced-effective gate on a many-partition cluster
    whose classes have multiplicity."""
    from kafka_assignment_optimizer_tpu.models.cluster import (
        Assignment,
        PartitionAssignment,
        Topology,
    )
    from kafka_assignment_optimizer_tpu.solvers import lp_round

    monkeypatch.setattr(inst_mod, "AGG_MEMBER_THRESHOLD", 0)
    topo = Topology.from_dict({str(b): f"r{b % 3}" for b in range(9)})
    # 48 partitions in 2 symmetric groups (classes with multiplicity
    # 24 — enough for the >=8x agg_effective collapse), current RF=3,
    # target RF=2 -> every class has 3 members, rf 2
    parts = [
        PartitionAssignment("t", p, [(p % 2) * 3, (p % 2) * 3 + 1,
                                     (p % 2) * 3 + 2])
        for p in range(48)
    ]
    current = Assignment(partitions=parts)
    inst = build_instance(current, list(range(9)), topo, target_rf=2)
    assert inst.agg_effective()  # multiplicity 6 over 3-member classes
    plan = lp_round.construct(inst)
    assert plan is not None
    assert inst.is_feasible(plan)
    assert (plan != inst.num_brokers)[:, :2].all()  # rf honored
    # quality: a certified-optimal RF shrink keeps 2 of 3 everywhere
    ex = optimize(solver="milp", current=current,
                  broker_list=list(range(9)), topology=topo, target_rf=2)
    assert inst.preservation_weight(plan) == ex.solve.objective


@pytest.mark.soak
def test_jumbo_full_certified():
    """THE r3 deliverable: the full 512-broker / 50k-partition jumbo
    decommission is solved to a PROVEN global optimum by the aggregated
    constructor — weight meets the bound, moves meet the exact max-flow
    minimum — in seconds, no annealing involved."""
    import time

    from kafka_assignment_optimizer_tpu.solvers.lp_round import construct

    sc, inst = _inst("jumbo", smoke=False)
    t0 = time.perf_counter()
    plan = construct(inst)
    construct_s = time.perf_counter() - t0
    assert plan is not None
    assert inst.is_feasible(plan)
    assert inst.move_count(plan) == inst.move_lower_bound_exact()
    assert inst.preservation_weight(plan) == inst.weight_upper_bound(
        level=0
    )
    assert inst.certify_optimal(plan)
    # generous wall bound: ~7 s measured; catches an accidental return
    # to the unaggregated 900 s regime
    assert construct_s < 60, f"jumbo construct took {construct_s:.1f}s"
