"""Sweep-parallel engine tests (the large-instance TPU path).

Covers: exact per-sweep scoring against the numpy oracle, invariant
preservation under thousands of parallel moves (no duplicate brokers, no
null slots), golden demo + random-cluster quality through the full
engine, and the conflict-thinning drift bound (every histogram moves at
most ±1 per broker per sweep)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance, optimize
from kafka_assignment_optimizer_tpu.solvers.tpu import arrays
from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed
from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
    chain_scores,
    sweep_once,
)

from tests.test_tpu_engine import random_cluster


def test_chain_scores_match_numpy_oracle(rng):
    current, brokers, topo = random_cluster(rng, 12, 25, 3, 3, drop=1)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    a = rng.integers(0, inst.num_brokers, size=(6, *inst.a0.shape)).astype(np.int32)
    w, pen = jax.jit(lambda a: chain_scores(m, a))(jnp.asarray(a))
    for i in range(a.shape[0]):
        v = inst.violations(a[i])
        expect_pen = (v["broker_balance"] + v["leader_balance"]
                      + v["rack_balance"] + v["part_rack_diversity"])
        assert int(w[i]) == inst.preservation_weight(a[i])
        assert int(pen[i]) == expect_pen


def test_sweep_preserves_hard_invariants(rng):
    """After many sweeps at high temperature, every chain keeps the
    hard-encoded constraint families intact and histogram drift per sweep
    stays within the thinning bound."""
    current, brokers, topo = random_cluster(rng, 10, 40, 3, 2, drop=1)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    seed = jnp.asarray(greedy_seed(inst), jnp.int32)
    a = jnp.broadcast_to(seed, (4, *seed.shape))
    step = jax.jit(lambda a, k, t: sweep_once(m, a, k, t))
    key = jax.random.PRNGKey(7)
    B = inst.num_brokers
    for i in range(30):
        key, sub = jax.random.split(key)
        prev = np.asarray(a)
        a = step(a, sub, jnp.float32(3.0))
        cur = np.asarray(a)
        for n in range(cur.shape[0]):
            v = inst.violations(cur[n])
            assert v["duplicate_in_partition"] == 0
            assert v["null_in_valid_slot"] == 0
            assert v["slot_out_of_range"] == 0
            # drift bound: per-broker totals move at most ±1 per sweep
            def hist(x):
                flat = np.where(inst.slot_valid, x, B)
                return np.bincount(flat.ravel(), minlength=B + 1)[:B]
            assert np.abs(hist(cur[n]) - hist(prev[n])).max() <= 1
        # sweeps must actually move things at high temperature
    assert (np.asarray(a)[0] != np.asarray(seed)).any()


def test_sweep_engine_demo_golden(demo):
    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="tpu", engine="sweep",
                   batch=16, rounds=48, steps_per_round=1)
    rep = res.report()
    assert rep["feasible"], rep
    assert rep["solver_engine"] == "sweep"
    assert res.replica_moves == 1
    assert res.solve.objective == res.instance.max_weight()


def test_sweep_engine_random_clusters_feasible(rng):
    current, brokers, topo = random_cluster(rng, 12, 30, 2, 3, drop=2)
    res = optimize(current, brokers, topo, solver="tpu", engine="sweep",
                   batch=8, rounds=64, steps_per_round=1)
    rep = res.report()
    assert rep["feasible"], rep
    exact = optimize(current, brokers, topo, solver="milp")
    # contract: the sweep engine is the *scale* engine — on adversarial
    # small clusters with exact-equality bands it must stay feasible and
    # near the ILP optimum (the chain engine, which is the default below
    # the size threshold, closes the last moves on instances this small)
    assert res.replica_moves <= exact.replica_moves + 2


def test_sweep_engine_leader_only_zero_moves():
    from kafka_assignment_optimizer_tpu.models.cluster import (
        Assignment,
        PartitionAssignment,
        Topology,
    )

    # replica sets perfectly balanced (4 per broker), leadership piled on
    # brokers 0..2 — the optimum is leader swaps only, zero replica moves
    parts = []
    for p in range(12):
        lead = p % 3
        foll = 3 + (p % 3)
        parts.append(PartitionAssignment("t", p, [lead, foll]))
    current = Assignment(partitions=parts)
    res = optimize(current, list(range(6)), Topology.single_rack(range(6)),
                   solver="tpu", engine="sweep",
                   batch=8, rounds=64, steps_per_round=1)
    rep = res.report()
    assert rep["feasible"], rep
    assert res.replica_moves == 0


def test_auto_engine_selection_by_size(rng, monkeypatch):
    """Below the threshold the chain engine runs; defaults report it.
    The constructor is neutralized — a constructed plan reports
    engine='construct', and this test pins the SEARCH default."""
    from kafka_assignment_optimizer_tpu.solvers.tpu import engine as eng

    monkeypatch.setattr(
        eng, "_construct_worker", lambda *a, **k: (None, False, False)
    )
    current, brokers, topo = random_cluster(rng, 8, 10, 2, 2, drop=0)
    res = optimize(current, brokers, topo, solver="tpu",
                   batch=8, rounds=4, steps_per_round=50)
    assert res.solve.stats["engine"] == "chain"


def test_sweep_migration_propagates_global_best(rng):
    """VERDICT r1 item 5: the sweep engine must share discoveries over
    the mesh axis. Seed 7 of 8 shards with a deliberately poisoned
    assignment and one shard with the near-optimal greedy seed; with a
    SINGLE snapshot (the final sweep) and freezing temperatures, every
    shard's returned best must reach the good shard's quality — only
    possible if the owner-broadcast migration delivered the candidate AND
    the migrant is harvested at the very snapshot where it arrives."""
    from jax.sharding import Mesh, PartitionSpec as P

    from kafka_assignment_optimizer_tpu.solvers.tpu.arrays import (
        geometric_temps,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
        best_key,
        make_sweep_solver_fn,
    )

    current, brokers, topo = random_cluster(rng, 12, 30, 2, 3, drop=1)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    good = jnp.asarray(greedy_seed(inst), jnp.int32)
    # poison: every replica of every partition on broker 0 — massively
    # infeasible, and single-site sweeps at T~0 cannot repair the
    # duplicate-broker rows (the incoming broker is rejected while its
    # twin occupies the row), so reaching `good` quality needs migration
    bad = jnp.zeros_like(good)
    n_dev = len(jax.devices())
    seeds = jnp.stack([good] + [bad] * (n_dev - 1))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    solve = make_sweep_solver_fn(n_chains=2, snapshot_every=8,
                                 axis_name="data")

    def shard_fn(m_rep, seeds_sh, keys_sh, temps):
        ba, bk, _curve = solve(m_rep, seeds_sh[0], keys_sh[0], temps)
        return ba[None], bk[None]

    from kafka_assignment_optimizer_tpu.parallel.mesh import _shard_map

    fn = jax.jit(
        _shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(), P("data"), P("data"), P()),
            out_specs=(P("data"), P("data")),
        )
    )
    temps = geometric_temps(1e-3, 1e-4, 6)  # frozen: no uphill moves
    keys = jax.random.split(jax.random.PRNGKey(0), n_dev)
    ba, bk = fn(m, seeds, keys, temps)
    bk = np.asarray(bk)
    w, pen = chain_scores(m, good[None])
    good_key = int(np.asarray(best_key(w, pen))[0])
    assert bk.max() >= good_key
    # every shard — including all poisoned ones — got the global best
    assert (bk >= good_key).all(), bk


@pytest.mark.soak
@pytest.mark.slow  # ~18 s; nightly. Tier-1 keeps the incremental-
# delta tracking pin (test_incremental_deltas_track_full_score) and
# the golden sweep trajectory.
def test_delta_stepper_bit_identical_to_from_scratch(rng):
    """The r5 delta engine (carried histograms updated from the kept
    moves) must replay the from-scratch formulation EXACTLY: same keys
    -> same populations, same per-chain bests, same curve. The reference
    loop below IS the r1-r4 stepper — from-scratch ``sweep_once`` /
    ``exchange_sweep`` each sweep, full rescoring at the snapshot
    cadence — so any carried-histogram drift (a wrong delta, a missed
    resync, a stale row) changes some proposal's accept decision and
    diverges the trajectory bit-visibly."""
    from kafka_assignment_optimizer_tpu.ops.score import moves_batch
    from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
        COMPOUND_EVERY,
        best_key,
        compound_sweep,
        exchange_sweep,
        make_sweep_stepper_fn,
    )

    current, brokers, topo = random_cluster(rng, 11, 23, 3, 3, drop=1)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    seed = jnp.asarray(greedy_seed(inst), jnp.int32)
    n_chains, snapshot_every, sweeps = 3, 4, 17  # odd tail: final snap
    a = jnp.broadcast_to(seed, (n_chains, *seed.shape))
    w0, p0 = chain_scores(m, a)
    mv0 = moves_batch(a, m)
    state0 = (a, best_key(w0, p0), mv0, a, jax.random.PRNGKey(5))
    temps = arrays.geometric_temps(2.0, 0.02, sweeps)

    # reference: the explicit from-scratch loop
    a_r, bk_r, bmv_r, ba_r, key_r = state0
    curve_r = []
    for i in range(sweeps):
        key_r, sub = jax.random.split(key_r)
        if i % COMPOUND_EVERY == COMPOUND_EVERY - 1:
            a_r = compound_sweep(m, a_r, sub, temps[i])
        elif i % 2 == 1:
            a_r = exchange_sweep(m, a_r, sub, temps[i])
        else:
            a_r = sweep_once(m, a_r, sub, temps[i])
        if i % snapshot_every == snapshot_every - 1 or i == sweeps - 1:
            w, pen = chain_scores(m, a_r)
            k = best_key(w, pen)
            mv = moves_batch(a_r, m)
            improved = jnp.logical_or(
                k > bk_r, jnp.logical_and(k == bk_r, mv < bmv_r)
            )
            bmv_r = jnp.where(improved, mv, bmv_r)
            bk_r = jnp.where(improved, k, bk_r)
            ba_r = jnp.where(improved[:, None, None], a_r, ba_r)
        curve_r.append(int(jnp.max(bk_r)))

    stepper = jax.jit(make_sweep_stepper_fn(n_chains, snapshot_every))
    (a_d, bk_d, bmv_d, ba_d, _key), _top_a, _top_k, curve_d = stepper(
        m, state0, temps
    )
    np.testing.assert_array_equal(np.asarray(a_d), np.asarray(a_r))
    np.testing.assert_array_equal(np.asarray(bk_d), np.asarray(bk_r))
    np.testing.assert_array_equal(np.asarray(bmv_d), np.asarray(bmv_r))
    np.testing.assert_array_equal(np.asarray(ba_d), np.asarray(ba_r))
    np.testing.assert_array_equal(np.asarray(curve_d), np.asarray(curve_r))


def test_site_hist_deltas_exact_vs_rebuild(rng):
    """Unit-level pin of the delta engine's bookkeeping: after a kept
    site sweep, the carried histograms equal a from-scratch rebuild of
    the applied population, integer for integer — for both the replace
    and leader-swap move shapes at a temperature hot enough to keep
    many of each."""
    from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
        _histograms,
        _site_sweep_delta,
    )

    current, brokers, topo = random_cluster(rng, 10, 30, 3, 2, drop=1)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    seed = jnp.asarray(greedy_seed(inst), jnp.int32)
    a = jnp.broadcast_to(seed, (4, *seed.shape))
    _f, _r, cnt, lcnt, rcnt = _histograms(m, a)
    key = jax.random.PRNGKey(11)
    step = jax.jit(
        lambda a, c, l, r, k: _site_sweep_delta(
            m, a, c, l, r, k, jnp.float32(3.0)
        )
    )
    for _ in range(12):
        key, sub = jax.random.split(key)
        a, cnt, lcnt, rcnt = step(a, cnt, lcnt, rcnt, sub)
        _f, _r, cnt2, lcnt2, rcnt2 = _histograms(m, a)
        np.testing.assert_array_equal(np.asarray(cnt), np.asarray(cnt2))
        np.testing.assert_array_equal(np.asarray(lcnt), np.asarray(lcnt2))
        np.testing.assert_array_equal(np.asarray(rcnt), np.asarray(rcnt2))


def test_sweep_solver_pallas_scorer_bit_identical(rng):
    """The TPU hot path routes per-sweep rescoring through the Pallas
    kernel (VERDICT r1 item 3). The kernel and the XLA scatter scorer
    return identical integers, so the whole sweep trajectory — accepts,
    thinning, snapshots — must be bit-identical between scorers. CI runs
    the kernel in interpret mode; on TPU the same code path compiles via
    Mosaic."""
    from kafka_assignment_optimizer_tpu.solvers.tpu.arrays import (
        geometric_temps,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
        make_sweep_solver_fn,
    )

    current, brokers, topo = random_cluster(rng, 10, 16, 2, 2, drop=1)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    seed = jnp.asarray(greedy_seed(inst), jnp.int32)
    key = jax.random.PRNGKey(7)
    temps = geometric_temps(2.0, 0.02, 10)
    outs = {}
    for scorer in ("xla", "pallas-interpret"):
        solve = make_sweep_solver_fn(n_chains=3, snapshot_every=4,
                                     scorer=scorer)
        ba, bk, curve = jax.jit(solve)(m, seed, key, temps)
        outs[scorer] = (np.asarray(ba), int(bk), np.asarray(curve))
    a_x, k_x, c_x = outs["xla"]
    a_p, k_p, c_p = outs["pallas-interpret"]
    assert k_x == k_p
    np.testing.assert_array_equal(a_x, a_p)
    np.testing.assert_array_equal(c_x, c_p)


@pytest.mark.soak
@pytest.mark.slow  # ~15 s; nightly. The kernel-inside-shard_map vma
# regression is also exercised tier-1 by the sharded interpret parity
# pin (test_mesh_sharding.py), which dispatches the same bundle under
# shard_map on every run.
def test_sweep_pallas_scorer_inside_shard_map(rng):
    """Regression for the r2 TPU bench crash: pallas_call's plain
    ShapeDtypeStruct out_shapes have no vma annotation, which
    jax>=0.9's shard_map varying-manual-axes check rejects — a failure
    mode only the TPU path hit, because the Pallas scorer route is
    TPU-only and every CPU test ran scorer='xla'. This runs the kernel
    (interpret mode) through the production shard_map wrapper
    (parallel.mesh, check_vma=False) on the 8-device CPU mesh and pins
    trajectory parity with the XLA scorer across shards."""
    from kafka_assignment_optimizer_tpu.parallel.mesh import (
        best_of,
        make_mesh,
        solve_on_mesh,
    )

    current, brokers, topo = random_cluster(rng, 10, 16, 2, 2, drop=1)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    seed = jnp.asarray(greedy_seed(inst), jnp.int32)
    mesh = make_mesh()
    outs = {}
    for scorer in ("xla", "pallas-interpret"):
        _state, pop_a, pop_k, _curve = solve_on_mesh(
            m, seed, jax.random.PRNGKey(3), mesh,
            chains_per_device=2, rounds=8, steps_per_round=1,
            engine="sweep", scorer=scorer,
        )
        outs[scorer] = best_of(pop_a, pop_k)
    a_x, k_x = outs["xla"]
    a_p, k_p = outs["pallas-interpret"]
    assert k_x == k_p
    np.testing.assert_array_equal(a_x, a_p)
