"""Pipelined ladder dispatch bit-parity (ISSUE 4, docs/PIPELINE.md).

The double-buffered dispatcher overlaps host boundary work with device
chunks by dispatching chunk i+1 before chunk i is retired. That is pure
scheduling: PRNG keys are pre-split in deterministic order and the sweep
state carries its own RNG, so the pipelined and synchronous
(``pipeline=False``) solves must agree BIT FOR BIT — final plan, best
curve, checkpoint contents, and checkpoint-resume replay — for both
engines, including across a forced mid-ladder Pallas→XLA fallback.

Boundary optimality certificates are disabled via ``cert_min_savings_s``
in the strict-parity tests: whether a certificate check RUNS depends on
wall-clock estimates (cold vs warm chunks), which is time-dependent by
design — the resulting plan is a proven optimum either way, but an
early-stopped curve is legitimately shorter.
"""

from __future__ import annotations

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu.api import optimize
from kafka_assignment_optimizer_tpu.models.cluster import (
    Assignment,
    PartitionAssignment,
    Topology,
)

# a generous never-binding budget: forces the finer 8-piece sweep chunk
# schedule (and chain chunking) without any risk of a timeout making
# which chunk is last depend on the clock
NO_DEADLINE = 3600.0


def random_cluster(rng, n_brokers, n_parts, rf, n_racks, drop=0):
    parts = []
    for p in range(n_parts):
        reps = rng.choice(n_brokers, size=rf, replace=False).tolist()
        parts.append(PartitionAssignment("t", p, [int(b) for b in reps]))
    topo = Topology(rack_of={b: f"r{b % n_racks}" for b in range(n_brokers)})
    brokers = list(range(n_brokers - drop))
    return Assignment(partitions=parts), brokers, topo


def _solve(cluster, pipeline, engine, checkpoint=None, **kw):
    # precompile=True switches the host-side constructor races off (the
    # engine's own deterministic knob): a race worker finishing between
    # two particular chunks is wall-clock-dependent and would make the
    # curve length an accident of thread scheduling, not a pipelining
    # property. cert_min_savings_s=1e9 pins the boundary certificate
    # off for the same reason (see module docstring).
    current, brokers, topo = cluster
    return optimize(
        current, brokers, topo, solver="tpu", engine=engine, seed=0,
        batch=8, pipeline=pipeline, time_limit_s=NO_DEADLINE,
        cert_min_savings_s=1e9, precompile=True, checkpoint=checkpoint,
        **kw,
    )


def _assert_parity(r_pipe, r_sync):
    s_p, s_s = r_pipe.solve.stats, r_sync.solve.stats
    assert np.array_equal(r_pipe.solve.a, r_sync.solve.a)
    assert r_pipe.solve.objective == r_sync.solve.objective
    assert s_p["moves"] == s_s["moves"]
    assert s_p["rounds_run"] == s_s["rounds_run"]
    assert s_p["score_curve"] == s_s["score_curve"]
    assert s_p["feasible"] is True


def test_sweep_pipelined_bit_identical_to_sync(rng):
    cluster = random_cluster(rng, 12, 48, 3, 3, drop=1)
    r_pipe = _solve(cluster, True, "sweep", rounds=32)
    r_sync = _solve(cluster, False, "sweep", rounds=32)
    # the flag actually selected the dispatcher under test: 4 chunks of
    # 8 sweeps (time-limited sweep schedule), speculation engaged
    assert r_pipe.solve.stats["pipeline"] is True
    assert r_sync.solve.stats["pipeline"] is False
    _assert_parity(r_pipe, r_sync)


def test_chain_pipeline_flag_is_inert_and_identical(rng):
    """The chain engine's boundary reseed is a data dependency, so it
    never speculates — pipeline=True must be a no-op, not a divergence."""
    cluster = random_cluster(rng, 10, 20, 2, 2, drop=1)
    kw = dict(rounds=8, steps_per_round=120)
    r_pipe = _solve(cluster, True, "chain", **kw)
    r_sync = _solve(cluster, False, "chain", **kw)
    assert r_pipe.solve.stats["pipeline"] is False  # never speculated
    _assert_parity(r_pipe, r_sync)


def test_checkpoint_and_resume_replay_identical(rng, tmp_path):
    """Pipelined and synchronous solves write identical checkpoints,
    and a resume from either replays to the same plan (SURVEY.md §5:
    re-solves never regress below the checkpoint)."""
    from kafka_assignment_optimizer_tpu.models.instance import (
        build_instance,
    )
    from kafka_assignment_optimizer_tpu.utils import checkpoint as ckpt

    cluster = random_cluster(rng, 12, 48, 3, 3, drop=1)
    ck_p = str(tmp_path / "pipe" / "ck.npz")
    ck_s = str(tmp_path / "sync" / "ck.npz")
    r_pipe = _solve(cluster, True, "sweep", rounds=32, checkpoint=ck_p)
    r_sync = _solve(cluster, False, "sweep", rounds=32, checkpoint=ck_s)
    _assert_parity(r_pipe, r_sync)
    inst = build_instance(*cluster)
    a_p, a_s = ckpt.load(ck_p, inst), ckpt.load(ck_s, inst)
    assert a_p is not None and np.array_equal(a_p, a_s)
    # resume: both modes warm-start from their checkpoint and replay to
    # the same answer again
    r_pipe2 = _solve(cluster, True, "sweep", rounds=32, checkpoint=ck_p)
    r_sync2 = _solve(cluster, False, "sweep", rounds=32, checkpoint=ck_s)
    assert r_pipe2.solve.stats["resumed_from_checkpoint"] is True
    assert r_sync2.solve.stats["resumed_from_checkpoint"] is True
    _assert_parity(r_pipe2, r_sync2)
    assert np.array_equal(r_pipe2.solve.a, r_pipe.solve.a)


@pytest.mark.parametrize("pipeline", [True, False])
def test_forced_midladder_pallas_fallback_parity(rng, monkeypatch,
                                                 pipeline):
    """A Mosaic lowering failure on the SECOND pallas dispatch (chunk 1
    — mid-ladder, so the pipelined path must drain its in-flight
    speculation, retry synchronously, and re-enter) falls back to the
    XLA scorer and still produces the synchronous solve's exact answer.
    CPU has no Mosaic path, so the TPU platform answer is simulated
    (scorer='pallas' decision) and the pallas-tagged dispatches are
    delegated to the XLA scorer — which is trajectory-bit-identical by
    the pinned scorer-parity contract (tests/test_sweep.py)."""
    from kafka_assignment_optimizer_tpu.parallel import mesh as pmesh
    from kafka_assignment_optimizer_tpu.utils import platform as plat

    monkeypatch.setattr(plat, "ensure_backend", lambda: "tpu")

    real = pmesh.solve_on_mesh
    real_lanes = pmesh.solve_lanes
    pallas_calls = {"n": 0}

    def _intercept(kw):
        """Shared fallback simulation for both dispatch shapes (the
        portfolio path ships chunks through solve_lanes)."""
        if kw.get("scorer") == "pallas":
            pallas_calls["n"] += 1
            if pallas_calls["n"] == 2:  # mid-ladder lowering failure
                raise RuntimeError(
                    "Mosaic lowering failed (forced test fallback)"
                )
            kw = dict(kw, scorer="xla")
        return kw

    def fake_solve_on_mesh(*args, **kw):
        return real(*args, **_intercept(kw))

    def fake_solve_lanes(*args, **kw):
        return real_lanes(*args, **_intercept(kw))

    monkeypatch.setattr(pmesh, "solve_on_mesh", fake_solve_on_mesh)
    monkeypatch.setattr(pmesh, "solve_lanes", fake_solve_lanes)

    cluster = random_cluster(rng, 12, 48, 3, 3, drop=1)
    res = _solve(cluster, pipeline, "sweep", rounds=32)
    st = res.solve.stats
    assert pallas_calls["n"] == 2  # chunk 0 ran pallas, chunk 1 failed
    assert "pallas_fallback" in st and "Mosaic" in st["pallas_fallback"]
    assert st["scorer"] == "xla"
    assert st["rounds_run"] == 32  # the fallback lost no chunks

    # the baseline: no simulated TPU, plain XLA sweep, synchronous —
    # the answer every fallback path must reproduce bit-for-bit
    monkeypatch.setattr(plat, "ensure_backend", lambda: "cpu")
    monkeypatch.setattr(pmesh, "solve_on_mesh", real)
    base = _solve(cluster, False, "sweep", rounds=32)
    assert np.array_equal(res.solve.a, base.solve.a)
    assert st["score_curve"] == base.solve.stats["score_curve"]


def test_batch_lane_pipeline_parity(rng):
    """solve_tpu_batch: pipelined and synchronous batched ladders agree
    per lane, bit for bit."""
    from kafka_assignment_optimizer_tpu.models.instance import (
        build_instance,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu.engine import (
        solve_tpu_batch,
    )

    insts = [
        build_instance(*random_cluster(rng, 12, 40 + 4 * i, 3, 3, drop=1))
        for i in range(3)
    ]
    kw = dict(engine="sweep", rounds=32, time_limit_s=NO_DEADLINE)
    r_pipe = solve_tpu_batch(insts, seeds=0, pipeline=True, **kw)
    r_sync = solve_tpu_batch(insts, seeds=0, pipeline=False, **kw)
    assert r_pipe[0].stats["pipeline"] is True
    assert r_sync[0].stats["pipeline"] is False
    for a, b in zip(r_pipe, r_sync):
        assert np.array_equal(a.a, b.a)
        assert a.stats["score_curve"] == b.stats["score_curve"]
        assert a.stats["moves"] == b.stats["moves"]
