"""Differential fuzz of the lp_solve dialect + certificate soak
(VERDICT r2 item 5).

The bundled CLI (``native/lp_cli.cpp``) is the de-facto reference
solver when no system ``lp_solve`` exists, so its whole pipeline —
``emit_lp`` -> subprocess -> ``-S4`` parse -> decode — is held to the
in-process exact MILP on random lopsided clusters: mixed per-topic RF
maps, 1-broker racks, broker removals and additions. Reference dialect:
``/root/reference/README.md:144-185``.

Soak mode (opt-in, release-blocking on any mismatch): set
``KAO_SOAK=<n>`` to multiply the trial counts, e.g.::

    KAO_SOAK=50 python -m pytest tests/test_lp_fuzz.py -q

which runs ~50x the CI volume of both the dialect fuzz and the
certificate-soundness soak (``docs/OPTIMALITY.md`` claims under
adversarial evidence). CI keeps the bounded default so the suite stays
fast.
"""

from __future__ import annotations

import os

# Soak volumes compile hundreds of DISTINCT tiny executables in one
# process; jaxlib's persistent-cache write path (compilation_cache.
# put_executable_and_time -> executable serialization) segfaulted
# under that load at KAO_SOAK=60 on CPU (reproduced twice; single
# thread, crash inside jaxlib — not framework code). The cache buys
# nothing for one-off tiny shapes, so soak runs opt out before the
# first solve can enable it. Effective when this file runs standalone
# (the documented soak invocation); inside the full suite another
# module may have enabled the cache first, but CI volume (KAO_SOAK=1)
# never approaches the crash load.
os.environ.setdefault("KAO_JIT_CACHE", "off")

import sys

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance, optimize
from kafka_assignment_optimizer_tpu.models.cluster import (
    Assignment,
    PartitionAssignment,
    Topology,
)
from kafka_assignment_optimizer_tpu.solvers.lp import (
    lp_solve_available,
    solve_lp_solve,
)
from kafka_assignment_optimizer_tpu.solvers.milp import solve_milp

# soak tier (VERDICT r4 item 5): differential fuzz + certificate soak
# are release gates, not commit gates — excluded from the default run
# (pyproject addopts -m "not soak"); run with -m soak / -m "". The
# slow marker enforces the same exclusion under gates that pass their
# own -m (which OVERRIDES addopts, silently re-admitting soak tests):
# these two runs cost ~110 s of a tier-1 budget the commit gate
# cannot spare, and their contract has always been nightly.
pytestmark = [pytest.mark.soak, pytest.mark.slow]

SOAK = int(os.environ.get("KAO_SOAK", "1"))


def random_lopsided(rng):
    """A cluster built to stress the dialect and the bounds: several
    topics with DIFFERENT target RFs (per-topic RF map), racks of very
    unequal size including 1-broker racks, and a broker list that may
    drop and/or add brokers."""
    n_b = int(rng.integers(5, 13))
    n_topics = int(rng.integers(1, 4))
    parts = []
    rf_map = {}
    for t in range(n_topics):
        name = f"t{t}"
        cur_rf = int(rng.integers(1, min(4, n_b) + 1))
        if rng.random() < 0.5:
            rf_map[name] = int(rng.integers(1, min(4, n_b) + 1))
        for p in range(int(rng.integers(2, 8))):
            reps = rng.choice(n_b, size=cur_rf, replace=False)
            parts.append(
                PartitionAssignment(name, p, [int(b) for b in reps])
            )
    # lopsided racks: rack 0 hoards brokers, the last rack often has 1
    n_racks = int(rng.integers(1, 4))
    add = int(rng.integers(0, 3))
    all_ids = list(range(n_b + add))
    rack = {
        b: f"r{0 if b % 4 < 2 else (b % n_racks)}" for b in all_ids
    }
    rack[all_ids[-1]] = f"r{n_racks}"  # a 1-broker rack
    drop = int(rng.integers(0, n_b)) if rng.random() < 0.5 else None
    brokers = [b for b in all_ids if b != drop]
    return dict(
        current=Assignment(partitions=parts),
        broker_list=brokers,
        topology=Topology.from_dict(rack),
        target_rf=rf_map or None,
    )


@pytest.mark.skipif(
    not lp_solve_available(),
    reason="no lp_solve binary and bundled lp_cli failed to build",
)
def test_lp_dialect_differential_fuzz(rng):
    """emit_lp -> lp_cli -> parse == in-process exact MILP, on every
    random lopsided cluster: same optimal objective, feasible decode.
    Any mismatch is a release blocker."""
    trials = 8 * SOAK
    compared = hard = 0
    for trial in range(trials):
        kw = random_lopsided(rng)
        try:
            inst = build_instance(**kw)
        except ValueError:
            continue  # RF > broker count after a drop: invalid input
        ex = solve_milp(inst)
        if not ex.optimal:
            continue
        try:
            lp = solve_lp_solve(inst, time_limit_s=15.0)
        except RuntimeError as e:
            # ONLY the rc=7 no-incumbent case may be skip-counted: a
            # search-depth pathology of the bundled DFS on extreme
            # exact-band instances, NOT a dialect defect (the emitted
            # LP was verified satisfiable by the MILP optimum when
            # this class was first hit) — and measured ZERO since the
            # round-4 phase-1 restart ladder. Every other RuntimeError
            # (CLI crash, overrun, malformed decode) is a real defect
            # and must fail the fuzz, not hide in the tally.
            if "found no solution within" not in str(e):
                raise
            hard += 1
            continue
        compared += 1
        assert inst.is_feasible(lp.a), trial
        if lp.optimal:
            assert lp.objective == ex.objective, (
                f"trial {trial}: lp_solve {lp.objective} "
                f"!= milp {ex.objective}"
            )
        else:  # timeout incumbent may only undershoot
            assert lp.objective <= ex.objective, trial
    # visible under -s: the soak evidence note in docs/OPTIMALITY.md
    # quotes this tally (hard == rc=7 skips; zero since the round-4
    # phase-1 restart ladder)
    print(f"[lp-fuzz] compared={compared} hard_rc7={hard}",
          file=sys.stderr)
    assert compared >= max(1, (compared + hard) // 2), (compared, hard)


def test_certificate_soundness_soak(rng):
    """Zero false ``proven_optimal``: every certificate the TPU engine
    emits on random lopsided clusters must equal the exact MILP optimum.
    Extends ``test_bounds.test_proof_claims_sound_on_random_clusters``
    to soak volume under ``KAO_SOAK`` — the single most important
    property of the bounds stack, now also covering per-topic RF maps
    and 1-broker racks."""
    import jax

    trials = 4 * SOAK
    proved = 0
    for trial in range(trials):
        if trial and trial % 20 == 0:
            # hundreds of distinct tiny executables in one process
            # eventually segfault jaxlib's XLA:CPU compile on this
            # host (reproduced at ~trial 180+ with the persistent
            # cache BOTH on and off; 126 GB free, so not memory —
            # consistent with the AOT machine-feature mismatch
            # jaxlib warns about). Dropping the executables
            # periodically keeps the soak inside the stable regime.
            jax.clear_caches()
        kw = random_lopsided(rng)
        try:
            r = optimize(solver="tpu", seed=trial, rounds=32, **kw)
        except ValueError:
            continue
        s = r.solve.stats
        assert s["feasible"], trial
        if s["proved_optimal"]:
            proved += 1
            ex = optimize(solver="milp", **kw)
            assert ex.solve.optimal
            assert r.solve.objective == ex.solve.objective, trial
            assert r.replica_moves <= ex.replica_moves, trial
    if SOAK > 1:  # CI volume may legitimately prove 0 of 4
        assert proved >= SOAK // 2


def test_agg_bounds_soak(rng):
    """The aggregated LP/MILP bounds (the jumbo-certifying tier) never
    undercut the exact optimum — soak companion to
    ``tests/test_agg_bounds.py`` on the lopsided generator."""
    trials = 4 * SOAK
    for trial in range(trials):
        kw = random_lopsided(rng)
        try:
            inst = build_instance(**kw)
        except ValueError:
            continue
        ex = solve_milp(inst)
        if not ex.optimal:
            continue
        for bound in (inst._kept_weight_agg(),
                      inst._kept_weight_agg(integer=True)):
            assert bound is not None, trial
            assert bound >= ex.objective, (
                f"trial {trial}: aggregated bound {bound} undercuts "
                f"exact optimum {ex.objective}"
            )
