"""Proposal-kernel parity (the fused Mosaic hot path, VERDICT r1 item 3).

The Pallas proposal kernel (``ops.propose_pallas``) must reproduce the
XLA proposal evaluator (``sweep.propose_site``) bit-for-bit given the
same random bits — same slots, same incoming brokers, same accepts, same
priorities — so the sweep trajectory is implementation-independent and
the CPU CI (interpret mode) executes the very code path the TPU runs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance
from kafka_assignment_optimizer_tpu.ops.propose_pallas import (
    propose_site_pallas,
)
from kafka_assignment_optimizer_tpu.solvers.tpu import arrays
from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed
from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
    _histograms,
    propose_site,
    sweep_once,
    thin_apply,
)

from tests.test_tpu_engine import random_cluster


def _instance(rng, nb=14, npart=40, rf=3, nr=3, drop=1):
    current, brokers, topo = random_cluster(rng, nb, npart, rf, nr,
                                            drop=drop)
    inst = build_instance(current, brokers, topo)
    return inst, arrays.from_instance(inst)


def _chains(m, inst, rng, n):
    a0 = np.asarray(greedy_seed(inst))
    a = np.broadcast_to(a0, (n, *a0.shape)).copy()
    # perturb: random legal-ish noise so histograms/penalties differ
    sl = rng.integers(0, inst.max_rf, size=(n, inst.num_parts))
    bk = rng.integers(0, inst.num_brokers, size=(n, inst.num_parts))
    a[np.arange(n)[:, None], np.arange(inst.num_parts)[None, :], sl] = bk
    a[~np.broadcast_to(inst.slot_valid, a.shape)] = inst.num_brokers
    return jnp.asarray(a, jnp.int32)


@pytest.mark.parametrize("temp", [2.0, 0.02])
def test_proposals_bit_identical(rng, temp):
    inst, m = _instance(rng)
    a = _chains(m, inst, rng, 5)
    bits = jax.random.bits(jax.random.PRNGKey(3), (*a.shape[:2], 8),
                           jnp.uint32)
    px = jax.jit(lambda a, b: propose_site(m, a, b, temp))(a, bits)
    pp = jax.jit(
        lambda a, b: propose_site_pallas(m, a, b, temp, hists=_histograms,
                                         interpret=True)
    )(a, bits)
    for f in px._fields:
        x = np.asarray(getattr(px, f))
        p = np.asarray(getattr(pp, f))
        np.testing.assert_array_equal(x, p, err_msg=f)


@pytest.mark.soak
@pytest.mark.slow  # ~20 s; nightly. Tier-1 keeps kernel-vs-XLA parity
# via test_sweep_solver_pallas_scorer_bit_identical and the
# exchange-counts pin below.
def test_sweep_trajectory_bit_identical_with_kernel(rng):
    """Full sweeps through thin_apply: the applied population must be
    byte-equal between the XLA and kernel proposal paths."""
    inst, m = _instance(rng, nb=10, npart=30, rf=2, nr=2)
    a = _chains(m, inst, rng, 4)
    key = jax.random.PRNGKey(9)
    ax = ap = a
    for i, temp in enumerate((2.5, 1.0, 0.3, 0.02)):
        k = jax.random.fold_in(key, i)
        ax = jax.jit(lambda a, k: sweep_once(m, a, k, temp))(ax, k)
        ap = jax.jit(
            lambda a, k: sweep_once(
                m, a, k, temp,
                propose=lambda *args, **kw: propose_site_pallas(
                    *args, **kw, interpret=True
                ),
            )
        )(ap, k)
        np.testing.assert_array_equal(np.asarray(ax), np.asarray(ap),
                                      err_msg=f"sweep {i}")


def test_unequal_racks_and_rf1_partitions(rng):
    """Edge shapes: rf=1 rows (no lswap legal) and unequal rack sizes
    (per-rack bounds differ) must still match bit-for-bit."""
    current, brokers, topo = random_cluster(rng, 9, 24, 1, 3, drop=0)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    a = _chains(m, inst, np.random.default_rng(5), 3)
    bits = jax.random.bits(jax.random.PRNGKey(8), (*a.shape[:2], 8),
                           jnp.uint32)
    px = propose_site(m, a, bits, 1.0)
    pp = propose_site_pallas(m, a, bits, 1.0, hists=_histograms,
                             interpret=True)
    for f in px._fields:
        np.testing.assert_array_equal(np.asarray(getattr(px, f)),
                                      np.asarray(getattr(pp, f)),
                                      err_msg=f)
    # and the applied result agrees
    np.testing.assert_array_equal(
        np.asarray(thin_apply(m, a, px)), np.asarray(thin_apply(m, a, pp))
    )


@pytest.mark.soak
@pytest.mark.slow  # ~14 s; nightly. Tier-1 keeps the exchange
# count-preservation pin and the unequal-racks/rf1 shape pin.
def test_exchange_halves_bit_identical(rng):
    """The exchange-halves kernel reproduces the XLA reference exactly,
    and the full exchange sweep is byte-equal between paths."""
    from kafka_assignment_optimizer_tpu.ops.propose_pallas import (
        exchange_halves_pallas,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
        _exchange_halves_xla,
        exchange_sweep,
    )

    inst, m = _instance(rng, nb=13, npart=37, rf=3, nr=3)
    a = _chains(m, inst, rng, 5)
    N, P, R = a.shape
    lcnt = jnp.zeros((N, inst.num_brokers + 1), jnp.int32).at[
        jnp.arange(N)[:, None], a[:, :, 0]
    ].add(1)
    s_own = jnp.asarray(
        rng.integers(0, inst.max_rf, size=(N, P)) % np.maximum(
            np.asarray(m.rf)[None, :], 1
        ), jnp.int32)
    lead_other = jnp.asarray(rng.integers(0, 2, size=(N, P)), bool)
    b_other = jnp.asarray(
        rng.integers(0, inst.num_brokers, size=(N, P)), jnp.int32)
    hx = _exchange_halves_xla(m, a, lcnt, s_own, lead_other, b_other)
    hp = exchange_halves_pallas(m, a, lcnt, s_own, lead_other, b_other,
                                interpret=True)
    for i, name in enumerate(("b_own", "dw", "ddiv", "dlcnt", "legal")):
        np.testing.assert_array_equal(np.asarray(hx[i]),
                                      np.asarray(hp[i]), err_msg=name)

    # whole exchange sweeps, both paths, byte-equal populations
    ax = ap = a
    for i, temp in enumerate((2.0, 0.4, 0.02)):
        k = jax.random.fold_in(jax.random.PRNGKey(4), i)
        ax = jax.jit(lambda a, k: exchange_sweep(m, a, k, temp))(ax, k)
        ap = jax.jit(lambda a, k: exchange_sweep(
            m, a, k, temp,
            halves=lambda *args, **kw: exchange_halves_pallas(
                *args, **kw, interpret=True),
        ))(ap, k)
        np.testing.assert_array_equal(np.asarray(ax), np.asarray(ap),
                                      err_msg=f"exchange sweep {i}")


@pytest.mark.parametrize("temp", [2.0, 0.02])
def test_site_step_kernel_bit_identical(rng, temp):
    """The fused thinning path (propose kernel map outputs + site finish
    kernel, ``ops.thin_pallas``) must reproduce the XLA delta step —
    applied population AND carried-histogram updates — bit-for-bit."""
    from kafka_assignment_optimizer_tpu.ops.thin_pallas import (
        site_step_pallas,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
        _site_sweep_delta,
    )

    inst, m = _instance(rng)
    a = _chains(m, inst, rng, 5)
    _f, _r, cnt, lcnt, rcnt = jax.jit(_histograms)(m, a)
    key = jax.random.PRNGKey(21)
    ox = jax.jit(
        lambda a, c, l, r: _site_sweep_delta(m, a, c, l, r, key, temp)
    )(a, cnt, lcnt, rcnt)
    op = jax.jit(
        lambda a, c, l, r: site_step_pallas(m, a, c, l, r, key, temp,
                                            interpret=True)
    )(a, cnt, lcnt, rcnt)
    for name, x, p in zip(("a", "cnt", "lcnt", "rcnt"), ox, op):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(p),
                                      err_msg=name)


@pytest.mark.parametrize("temp", [2.0, 0.02])
def test_exchange_step_kernel_bit_identical(rng, temp):
    """The fused exchange thinning path (maps + finish kernels) must
    reproduce the XLA exchange delta step bit-for-bit."""
    from kafka_assignment_optimizer_tpu.ops.thin_pallas import (
        exchange_step_pallas,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
        _exchange_sweep_delta,
    )

    inst, m = _instance(rng, nb=13, npart=37, rf=3, nr=3)
    a = _chains(m, inst, rng, 5)
    _f, _r, cnt, lcnt, rcnt = jax.jit(_histograms)(m, a)
    key = jax.random.PRNGKey(33)
    ox = jax.jit(
        lambda a, c, l, r: _exchange_sweep_delta(m, a, c, l, r, key, temp)
    )(a, cnt, lcnt, rcnt)
    op = jax.jit(
        lambda a, c, l, r: exchange_step_pallas(m, a, c, l, r, key, temp,
                                                interpret=True)
    )(a, cnt, lcnt, rcnt)
    for name, x, p in zip(("a", "cnt", "lcnt", "rcnt"), ox, op):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(p),
                                      err_msg=name)


@pytest.mark.soak
def test_exchange_preserves_counts(rng):
    """The exchange move is count-invariant by construction: per-broker
    and per-rack replica totals must be untouched by any number of
    exchange sweeps (only leadership and diversity may change)."""
    from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
        exchange_sweep,
    )

    inst, m = _instance(rng, nb=12, npart=50, rf=2, nr=2)
    a = _chains(m, inst, rng, 4)
    before = np.sort(np.asarray(a).reshape(4, -1), axis=1)
    out = a
    for i in range(6):
        out = jax.jit(lambda a, k: exchange_sweep(m, a, k, 2.0))(
            out, jax.random.PRNGKey(i)
        )
    after = np.sort(np.asarray(out).reshape(4, -1), axis=1)
    np.testing.assert_array_equal(before, after)
    assert (np.asarray(out) != np.asarray(a)).any()  # it did something
