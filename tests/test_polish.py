"""Steepest-descent polish tests: every applied move must track the exact
numpy oracle (score never decreases, final state exactly rescored), and a
polished candidate must be 1-move locally optimal — no single replacement
or leader swap can improve it (verified by brute force)."""

import numpy as np
import jax.numpy as jnp
import pytest

from kafka_assignment_optimizer_tpu import build_instance, optimize
from kafka_assignment_optimizer_tpu.solvers.tpu import arrays
from kafka_assignment_optimizer_tpu.solvers.tpu.arrays import LAMBDA, SCALE_W
from kafka_assignment_optimizer_tpu.solvers.tpu.polish import polish_jit

from tests.test_tpu_engine import random_cluster


def exact_score(inst, a):
    v = inst.violations(a)
    pen = (v["broker_balance"] + v["leader_balance"] + v["rack_balance"]
           + v["part_rack_diversity"])
    return SCALE_W * inst.preservation_weight(a) - LAMBDA * pen


def brute_force_best_single_move(inst, a):
    """Max exact-score gain over ALL single moves (replace + lswap)."""
    P, R = a.shape
    B = inst.num_brokers
    base = exact_score(inst, a)
    best = 0
    for p in range(P):
        rf = int(inst.rf[p])
        row = set(int(x) for x in a[p, :rf])
        for s in range(rf):
            for b in range(B):
                if b in row:
                    continue
                cand = a.copy()
                cand[p, s] = b
                best = max(best, exact_score(inst, cand) - base)
        for s in range(1, rf):
            cand = a.copy()
            cand[p, 0], cand[p, s] = cand[p, s], cand[p, 0]
            best = max(best, exact_score(inst, cand) - base)
    return best


@pytest.mark.parametrize("case", [
    dict(n_brokers=8, n_parts=10, rf=2, n_racks=2, drop=1),
    dict(n_brokers=9, n_parts=8, rf=3, n_racks=3, drop=0),
    dict(n_brokers=10, n_parts=9, rf=1, n_racks=2, drop=2),  # RF=1 edge
])
def test_polish_reaches_local_optimum(case, rng):
    current, brokers, topo = random_cluster(rng, **case)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    for trial in range(3):
        a0 = rng.integers(0, inst.num_brokers, size=inst.a0.shape).astype(np.int32)
        # de-duplicate rows so a0 is a legal candidate (hard constraint C8)
        for p in range(inst.num_parts):
            rf = int(inst.rf[p])
            seen, pool = set(), [b for b in range(inst.num_brokers)]
            for s in range(rf):
                b = int(a0[p, s])
                if b in seen:
                    b = next(x for x in pool if x not in seen)
                a0[p, s] = b
                seen.add(b)
        out = np.asarray(polish_jit(m, jnp.asarray(a0)))
        # never worse, duplicates never introduced
        assert exact_score(inst, out) >= exact_score(inst, a0)
        v = inst.violations(out)
        assert v["duplicate_in_partition"] == 0 and v["null_in_valid_slot"] == 0
        # 1-move local optimality, brute-forced
        assert brute_force_best_single_move(inst, out) <= 0


def test_polish_fixes_single_bad_slot(demo):
    """Start from the known optimum with one slot vandalized; polish alone
    must restore an optimal-score plan (the demo's 1-move structure)."""
    current, brokers, topo = demo
    inst = build_instance(current, brokers, topo)
    from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed

    a = greedy_seed(inst)
    assert inst.move_count(a) == 1
    best = exact_score(inst, a)
    vandal = a.copy()
    vandal[4, 1] = (vandal[4, 1] + 4) % inst.num_brokers
    if vandal[4, 1] == vandal[4, 0]:
        vandal[4, 1] = (vandal[4, 1] + 1) % inst.num_brokers
    out = np.asarray(polish_jit(m := arrays.from_instance(inst), jnp.asarray(vandal)))
    assert exact_score(inst, out) >= best
    assert inst.move_count(out) <= 2


def test_engine_with_polish_still_golden(demo):
    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="tpu",
                   batch=16, rounds=4, steps_per_round=150)
    rep = res.report()
    assert rep["feasible"], rep
    assert res.replica_moves == 1
    assert res.solve.objective == res.instance.max_weight()
