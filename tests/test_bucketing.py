"""Shape-bucketing tests (solvers.tpu.bucket + arrays padding).

The contract under test, in three layers:

1. **Inertness** (the load-bearing property): a model lowered padded to
   a bucket shape scores every candidate bit-identically to the
   unpadded model — weights, penalties, histograms, move counts — and
   annealing sweeps never write into padded rows, so the padded solve
   explores exactly the real instance's search space.
2. **Solve equivalence**: a bucketed sweep solve of a constructor-proof
   instance returns the same certified quality (feasible, moves,
   objective, proved_optimal) as the unbucketed solve, with the plan
   verified by the numpy oracle either way.
3. **Executable reuse**: two different clusters landing in the same
   bucket share one compiled executable (compiles counted via a
   monkeypatched lowering hook).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance, optimize
from kafka_assignment_optimizer_tpu.models.cluster import (
    Assignment,
    PartitionAssignment,
    Topology,
)
from kafka_assignment_optimizer_tpu.ops.score import moves_batch
from kafka_assignment_optimizer_tpu.solvers.tpu import arrays, bucket
from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed
from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
    chain_scores,
    exchange_sweep,
    sweep_once,
)


def random_cluster(rng, n_brokers, n_parts, rf, n_racks, drop=0):
    parts = []
    for p in range(n_parts):
        reps = rng.choice(n_brokers, size=rf, replace=False).tolist()
        parts.append(PartitionAssignment("t", p, [int(b) for b in reps]))
    topo = Topology(rack_of={b: f"r{b % n_racks}" for b in range(n_brokers)})
    return Assignment(partitions=parts), list(range(n_brokers - drop)), topo


def test_ladder_monotone_aligned_and_idempotent():
    rungs = bucket.ladder(30)
    assert rungs == sorted(set(rungs))
    for r in rungs:
        assert r % 8 == 0
        assert bucket.part_bucket(r) == r  # a rung maps to itself
    for p in (1, 17, 200, 999, 10_000, 50_000):
        b = bucket.part_bucket(p)
        assert b >= p
        assert b <= max(2 * p, 48)  # growth factor bounds the padding
    for r in (1, 2, 3, 4, 5, 6, 7, 8, 9, 17):
        assert bucket.rf_bucket(r) >= r


def test_env_overrides(monkeypatch):
    monkeypatch.setenv("KAO_BUCKETS", "off")
    assert not bucket.enabled()
    assert bucket.part_bucket(37) == 37
    assert bucket.rf_bucket(3) == 3
    monkeypatch.setenv("KAO_BUCKETS", "64,1024")
    assert bucket.enabled()
    assert bucket.part_bucket(37) == 64
    assert bucket.part_bucket(65) == 1024
    assert bucket.part_bucket(5000) == 5000  # above the custom top rung
    assert bucket.ladder(5) == [64, 1024]
    monkeypatch.setenv("KAO_BUCKETS", "not,numbers")
    assert bucket.part_bucket(37) == bucket.ladder(2)[1]  # default ladder


@pytest.mark.parametrize("case", [
    dict(n_brokers=8, n_parts=11, rf=2, n_racks=2, drop=1),
    dict(n_brokers=9, n_parts=25, rf=3, n_racks=3, drop=0),
    dict(n_brokers=12, n_parts=33, rf=4, n_racks=4, drop=2),
])
def test_padded_model_scores_bit_identical(case, rng):
    """Layer 1: padded vs unpadded scoring of the SAME candidates is
    bit-identical on every real quantity — fuzzed cluster shapes,
    random (including infeasible) candidate populations."""
    current, brokers, topo = random_cluster(rng, **case)
    inst = build_instance(current, brokers, topo)
    p_b, r_b = bucket.bucket_shape(inst)
    assert p_b > inst.num_parts  # the fuzz shapes really exercise padding
    m = arrays.from_instance(inst)
    mp = arrays.from_instance(inst, num_parts=p_b, max_rf=r_b)
    B, K = inst.num_brokers, inst.num_racks
    N = 6
    a = rng.integers(0, B, size=(N, inst.num_parts, inst.max_rf)).astype(
        np.int32
    )
    ap = np.stack([arrays.pad_candidate(x, mp) for x in a])
    w, pen = (np.asarray(x) for x in chain_scores(m, jnp.asarray(a)))
    wp, penp = (np.asarray(x) for x in chain_scores(mp, jnp.asarray(ap)))
    np.testing.assert_array_equal(w, wp)
    np.testing.assert_array_equal(pen, penp)
    np.testing.assert_array_equal(
        np.asarray(moves_batch(jnp.asarray(a), m)),
        np.asarray(moves_batch(jnp.asarray(ap), mp)),
    )
    # histograms agree on every real broker/rack bucket
    from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import _histograms

    _, _, cnt, lcnt, rcnt = _histograms(m, jnp.asarray(a))
    _, _, cntp, lcntp, rcntp = _histograms(mp, jnp.asarray(ap))
    np.testing.assert_array_equal(np.asarray(cnt)[:, :B],
                                  np.asarray(cntp)[:, :B])
    np.testing.assert_array_equal(np.asarray(lcnt)[:, :B],
                                  np.asarray(lcntp)[:, :B])
    np.testing.assert_array_equal(np.asarray(rcnt)[:, :K],
                                  np.asarray(rcntp)[:, :K])
    # oracle agreement: the device scores of the padded population equal
    # the numpy oracle's on the unpadded slice
    for i in range(N):
        v = inst.violations(a[i])
        real_pen = (v["broker_balance"] + v["leader_balance"]
                    + v["rack_balance"] + v["part_rack_diversity"])
        assert int(penp[i]) == real_pen
        assert int(wp[i]) == inst.preservation_weight(a[i])


def test_sweeps_never_write_padded_rows(rng):
    """Layer 1, dynamics: site and exchange sweeps on a padded
    population must leave every padded row all-null and keep the real
    rows' scores consistent with the numpy oracle."""
    current, brokers, topo = random_cluster(rng, 10, 21, 3, 2, drop=1)
    inst = build_instance(current, brokers, topo)
    p_b, r_b = bucket.bucket_shape(inst)
    mp = arrays.from_instance(inst, num_parts=p_b, max_rf=r_b)
    B = inst.num_brokers
    seed = arrays.pad_candidate(greedy_seed(inst), mp)
    a = jnp.broadcast_to(jnp.asarray(seed, jnp.int32), (4, p_b, r_b))
    key = jax.random.PRNGKey(3)
    for i in range(6):
        key, sub = jax.random.split(key)
        if i % 2 == 0:
            a = sweep_once(mp, a, sub, jnp.float32(2.0))
        else:
            a = exchange_sweep(mp, a, sub, jnp.float32(2.0))
    a = np.asarray(a)
    # padded partition rows and padded slot columns stay all-null
    assert (a[:, inst.num_parts:, :] == B).all()
    assert (a[:, :, inst.max_rf:] == B).all()
    w, pen = (np.asarray(x) for x in chain_scores(mp, jnp.asarray(a)))
    for i in range(a.shape[0]):
        real = a[i, : inst.num_parts, : inst.max_rf]
        v = inst.violations(real)
        assert v["duplicate_in_partition"] == 0
        assert v["null_in_valid_slot"] == 0
        real_pen = (v["broker_balance"] + v["leader_balance"]
                    + v["rack_balance"] + v["part_rack_diversity"])
        assert int(pen[i]) == real_pen
        assert int(w[i]) == inst.preservation_weight(real)


def _adversarial_profile_guard(sc):
    """The reuse tests rest on the adversarial gate profile (slack
    caps, no aggregation) — fail loudly on generator drift instead of
    silently testing the constructor path."""
    inst = build_instance(sc.current, sc.broker_list, sc.topology,
                          target_rf=sc.target_rf)
    assert not inst.caps_bind(), "generator drift: caps bind"
    assert not inst.agg_effective(), "generator drift: aggregation viable"
    return inst


@pytest.mark.soak
@pytest.mark.slow  # ~24 s; nightly. Tier-1 keeps the padded-row and
# ladder-dedup bucketing pins; quality identity re-proves nightly.
def test_bucketed_solve_quality_identical_to_unbucketed(monkeypatch):
    """Layer 2: the bucketed sweep solve of a constructor-proof
    instance certifies the same optimum as the unbucketed solve —
    identical moves, objective, proved_optimal, feasibility, and both
    plans verified by the numpy oracle. (Assignment bytes are not
    pinned across the two configs: the shapes differ, so the annealing
    trajectories legitimately differ between two equally certified
    optima; the certificate pins the quality exactly.)"""
    from kafka_assignment_optimizer_tpu.utils import gen

    sc = gen.SCENARIOS["adversarial"](**gen.SMOKE_KWARGS["adversarial"])
    _adversarial_profile_guard(sc)
    kw = dict(solver="tpu", seed=0, engine="sweep",
              cert_min_savings_s=1e9)  # no timing-dependent early stops
    monkeypatch.setenv("KAO_BUCKETS", "off")
    r_raw = optimize(**kw, **sc.kwargs)
    monkeypatch.delenv("KAO_BUCKETS")
    r_b = optimize(**kw, **sc.kwargs)
    s_raw, s_b = r_raw.solve.stats, r_b.solve.stats
    assert "bucket_parts" not in s_raw or (
        s_raw["bucket_parts"] == r_raw.instance.num_parts
    )
    assert s_b["bucket_parts"] > r_b.instance.num_parts
    for k in ("feasible", "proved_optimal", "moves"):
        assert s_raw[k] == s_b[k], (k, s_raw[k], s_b[k])
    assert r_raw.solve.objective == r_b.solve.objective
    assert s_b["proved_optimal"] and s_b["moves"] == sc.min_moves_lb
    for r in (r_raw, r_b):
        inst = r.instance
        assert inst.is_feasible(inst.encode(r.assignment))


def test_same_bucket_clusters_reuse_one_executable(monkeypatch):
    """Layer 3 (issue acceptance): two DIFFERENT clusters — different
    partition counts — landing in the same bucket reuse one compiled
    executable; compiles counted via a monkeypatched lowering hook."""
    from kafka_assignment_optimizer_tpu.parallel import mesh
    from kafka_assignment_optimizer_tpu.utils import gen

    sc1 = gen.adversarial(n_brokers=32, n_topics_low=11, n_topics_high=9,
                          parts_per_topic=10)  # 200 partitions
    sc2 = gen.adversarial(n_brokers=32, n_topics_low=11, n_topics_high=9,
                          parts_per_topic=9)   # 180 partitions
    i1, i2 = (_adversarial_profile_guard(s) for s in (sc1, sc2))
    assert i1.num_parts != i2.num_parts
    assert bucket.part_bucket(i1.num_parts) == bucket.part_bucket(
        i2.num_parts
    )

    compiles: list = []
    real = mesh._lower_and_compile

    def counting(fn, args):
        compiles.append(mesh._arg_signature(args))
        return real(fn, args)

    monkeypatch.setattr(mesh, "_lower_and_compile", counting)
    kw = dict(solver="tpu", seed=0, engine="sweep")
    r1 = optimize(**kw, **sc1.kwargs)
    after_first = len(compiles)
    r2 = optimize(**kw, **sc2.kwargs)
    assert r1.solve.stats["engine"] == "sweep"
    assert r2.solve.stats["engine"] == "sweep"
    assert r1.solve.stats["bucket_parts"] == r2.solve.stats["bucket_parts"]
    # the second cluster compiled NOTHING: its shapes hit the LRU
    assert len(compiles) == after_first, (
        f"same-bucket solve recompiled: {compiles[after_first:]}"
    )
    assert r1.report()["feasible"] and r2.report()["feasible"]
