"""Chaos-injection + graceful-degradation tests (ISSUE 6).

The acceptance contract (docs/RESILIENCE.md): for every armed fault
spec the solve/serve path returns a valid certified-or-degraded plan or
a structured 503 with Retry-After — no hangs, no uncaught exceptions —
and every ladder rung taken is visible in all three places at once
(``stats["degradations"]``, the trace's ``degrade`` marks, and the
``kao_degradations_total{rung=}`` counter). With chaos disarmed,
trajectories stay bit-identical.

One test per injection point (resilience.chaos.POINTS), plus unit
coverage for the Budget/backoff, the spec parser, the ladder collector,
and the circuit breaker.
"""

import threading
import time

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance
from kafka_assignment_optimizer_tpu import serve as srv
from kafka_assignment_optimizer_tpu.models.cluster import demo_assignment
from kafka_assignment_optimizer_tpu.obs import trace as otrace
from kafka_assignment_optimizer_tpu.resilience import (
    breaker as rbreaker,
    budget as rbudget,
    chaos,
    ladder,
)
from kafka_assignment_optimizer_tpu.solvers.tpu.engine import solve_tpu

# small-but-annealing solve knobs: enough budget that the demo instance
# reaches the device ladder (the constructor race does not certify at
# these knobs — pinned by the rung assertions themselves)
KNOBS = dict(seed=0, batch=8, rounds=4, steps_per_round=60)


@pytest.fixture(autouse=True)
def _clean_resilience():
    """Chaos/ladder/breaker state is process-global: every test starts
    and ends disarmed with zeroed counters."""
    chaos.disarm()
    chaos.reset_counters()
    ladder.reset()
    srv._BREAKER.reset()
    srv._BREAKER.configure(threshold=3, cooldown_s=30.0)
    yield
    chaos.disarm()
    chaos.reset_counters()
    ladder.reset()
    srv._BREAKER.reset()
    srv._BREAKER.configure(threshold=3, cooldown_s=30.0)


@pytest.fixture
def inst(demo):
    current, brokers, topo = demo
    return build_instance(current, brokers, topo)


def _degrade_rungs(report: dict) -> list:
    """All ``degrade`` mark rungs in a solve report's span tree."""
    out = []

    def walk(sp):
        if sp["name"] == "degrade":
            out.append(sp["attrs"]["rung"])
        for c in sp.get("spans", []):
            walk(c)

    walk(report["spans"])
    return out


def _assert_valid(inst, res):
    """A chaos-surviving solve must return a usable plan: feasible (or
    explicitly flagged degraded-infeasible) and shape-correct."""
    assert res.a.shape == (inst.num_parts, inst.max_rf)
    if res.stats.get("degraded"):
        assert res.stats["feasible"] == inst.is_feasible(res.a)
    else:
        assert inst.is_feasible(res.a)


# --------------------------------------------------------------------------
# budget / backoff units
# --------------------------------------------------------------------------


def test_budget_unlimited_passthrough():
    b = rbudget.Budget(None)
    assert b.remaining() is None and not b.expired()
    assert b.deadline is None
    assert b.cap(None) is None and b.cap(7.5) == 7.5


def test_budget_remaining_cap_expiry():
    b = rbudget.Budget(10.0, t0=time.perf_counter() - 4.0)
    left = b.remaining()
    assert 5.5 < left < 6.5
    assert b.cap(100.0) == pytest.approx(left, abs=0.5)
    assert b.cap(0.001) == 0.001  # tighter explicit timeout wins
    assert b.cap(None) == pytest.approx(left, abs=0.5)
    expired = rbudget.Budget(0.001, t0=time.perf_counter() - 1.0)
    assert expired.expired() and expired.remaining() == 0.0


def test_backoff_exponential_jittered_capped():
    for attempt in range(8):
        for _ in range(20):
            s = rbudget.backoff_s(attempt, base_s=0.1, cap_s=1.0,
                                  jitter=0.5)
            raw = min(0.1 * 2 ** attempt, 1.0)
            assert raw * 0.5 <= s <= raw * 1.5


def test_budget_sleep_backoff_never_overshoots_deadline():
    b = rbudget.Budget(0.05)
    t0 = time.perf_counter()
    slept = b.sleep_backoff(attempt=10, base_s=10.0, cap_s=10.0)
    assert slept <= 0.06  # clamped to the remaining budget, not 10 s
    assert time.perf_counter() - t0 < 1.0


# --------------------------------------------------------------------------
# chaos harness units
# --------------------------------------------------------------------------


def test_chaos_spec_parser_rejects_garbage():
    for bad in ("definitely_not_a_point", "pallas_fault:2.0",
                "pallas_fault:0.5:0", "seed=1", "", "nan_chunk:1:2:3"):
        with pytest.raises(ValueError):
            chaos.parse_spec(bad)


def test_chaos_spec_parses_full_grammar():
    points, seed, delay = chaos.parse_spec(
        "seed=7,delay=0.1,pallas_fault,nan_chunk:0.5,exec_evict:1:3,"
        "queue_overload:1:-1"
    )
    assert seed == 7 and delay == 0.1
    assert points["pallas_fault"] == {"prob": 1.0, "left": 1}
    assert points["nan_chunk"] == {"prob": 0.5, "left": 1}
    assert points["exec_evict"] == {"prob": 1.0, "left": 3}
    assert points["queue_overload"]["left"] == -1


def test_chaos_disarmed_is_noop():
    assert not chaos.armed()
    assert not chaos.fires("pallas_fault")
    chaos.raise_if("pallas_fault")  # no raise
    chaos.sleep_if("chunk_overrun")  # no sleep
    assert chaos.snapshot() == {"armed": 0, "spec": None, "fired": {}}


def test_chaos_fire_budget_consumed_and_counted():
    chaos.arm("pallas_fault:1:2")
    assert chaos.fires("pallas_fault")
    assert chaos.fires("pallas_fault")
    assert not chaos.fires("pallas_fault")  # budget of 2 spent
    assert chaos.snapshot()["fired"] == {"pallas_fault": 2}


def test_chaos_seeded_probability_replays():
    def run():
        chaos.arm("seed=123,nan_chunk:0.5:-1")
        return [chaos.fires("nan_chunk") for _ in range(32)]

    a, b = run(), run()
    assert a == b and True in a and False in a


def test_chaos_raise_if_shapes_the_exception():
    chaos.arm("nan_chunk,checkpoint_write")
    with pytest.raises(FloatingPointError):
        chaos.raise_if("nan_chunk", FloatingPointError)
    with pytest.raises(OSError):
        chaos.raise_if("checkpoint_write", OSError)
    chaos.arm("pallas_fault")
    with pytest.raises(chaos.ChaosFault) as ei:
        chaos.raise_if("pallas_fault")
    assert chaos.is_pallas_fault(ei.value)


def test_chaos_env_arm_typo_fails_loudly():
    import os
    import subprocess
    import sys

    p = subprocess.run(
        [sys.executable, "-c",
         "import kafka_assignment_optimizer_tpu.resilience.chaos"],
        env={**os.environ, "KAO_CHAOS": "not_a_point"},
        capture_output=True, text=True, cwd="/root/repo",
    )
    assert p.returncode != 0 and "not_a_point" in p.stderr


# --------------------------------------------------------------------------
# ladder units
# --------------------------------------------------------------------------


def test_ladder_counts_and_snapshot_predeclares_all_rungs():
    snap = ladder.snapshot()
    assert set(snap) == set(ladder.RUNGS)
    assert all(v == 0 for v in snap.values())
    ladder.note_rung("pallas_to_xla", chunk=3)
    assert ladder.snapshot()["pallas_to_xla"] == 1


def test_ladder_collector_outermost_owns_nested_rungs():
    with ladder.collect() as outer:
        ladder.note_rung("aot_to_jit")
        with ladder.collect() as inner:
            assert inner is None  # nested: feeds the outer list
            ladder.note_rung("sweep_to_chain")
    assert outer == ["aot_to_jit", "sweep_to_chain"]
    ladder.note_rung("transfer_retry")  # no active collector: only counted
    assert ladder.snapshot()["transfer_retry"] == 1


# --------------------------------------------------------------------------
# circuit breaker units
# --------------------------------------------------------------------------


def test_breaker_opens_at_threshold_and_probes():
    br = rbreaker.CircuitBreaker(threshold=2, cooldown_s=0.05)
    key = ("bucket", 1)
    br.record_failure(key)
    assert br.allow(key) == (True, 0.0)  # below threshold: closed
    br.record_failure(key)  # trips
    ok, retry = br.allow(key)
    assert not ok and retry > 0
    time.sleep(0.08)
    ok, _ = br.allow(key)  # cooldown passed: ONE probe admitted
    assert ok
    ok2, _ = br.allow(key)  # concurrent request behind the probe: shed
    assert not ok2
    br.record_success(key)  # probe succeeded: circuit closes
    assert br.allow(key) == (True, 0.0)
    assert br.snapshot()["trips_total"] == 1


def test_breaker_probe_failure_reopens_escalated():
    # cooldown large enough that the 0.1 s Retry-After floor never
    # masks the escalation (jitter is +/-25%: trip-2 min 0.75 s always
    # exceeds trip-1 max 0.625 s)
    br = rbreaker.CircuitBreaker(threshold=1, cooldown_s=0.5)
    key = ("bucket", 2)
    br.record_failure(key)  # trip 1
    _, retry1 = br.allow(key)
    time.sleep(0.7)
    ok, _ = br.allow(key)
    assert ok  # the probe
    br.record_failure(key)  # probe fails: re-open, escalated cooldown
    ok, retry2 = br.allow(key)
    assert not ok and retry2 > retry1
    assert br.snapshot()["trips_total"] == 2


def test_breaker_probe_release_unlatches():
    """A probe that concludes WITHOUT a solver verdict (shed on
    saturation, failed validation) must release the half-open latch —
    otherwise the circuit wedges open and no later request can probe."""
    br = rbreaker.CircuitBreaker(threshold=1, cooldown_s=0.05)
    key = ("bucket", 3)
    br.record_failure(key)  # trip
    time.sleep(0.08)
    ok, _ = br.allow(key)
    assert ok  # the probe
    ok2, _ = br.allow(key)
    assert not ok2  # latched behind the in-flight probe
    br.release_probe(key)  # probe shed pre-solver: no verdict
    ok3, _ = br.allow(key)
    assert ok3  # a later request may probe again
    br.record_success(key)
    assert br.allow(key) == (True, 0.0)
    assert br.snapshot()["trips_total"] == 1


# --------------------------------------------------------------------------
# engine injection points — one per point, rung observable end to end
# --------------------------------------------------------------------------


def test_point_compile_fail_degrades_aot_to_jit(inst):
    # the injection point sits at the AOT compile site, which only a
    # COLD executable-cache key reaches — under the full suite earlier
    # tests have already compiled this bucket, so start cold
    from kafka_assignment_optimizer_tpu.parallel.mesh import (
        clear_exec_cache,
    )

    clear_exec_cache()
    chaos.arm("compile_fail")
    res = solve_tpu(inst, **KNOBS)
    _assert_valid(inst, res)
    assert "aot_to_jit" in res.stats.get("degradations", [])
    assert ladder.snapshot()["aot_to_jit"] >= 1
    assert chaos.snapshot()["fired"].get("compile_fail") == 1


def test_point_device_transfer_retried(inst):
    chaos.arm("device_transfer")
    res = solve_tpu(inst, **KNOBS)
    _assert_valid(inst, res)
    assert "transfer_retry" in res.stats.get("degradations", [])
    assert ladder.snapshot()["transfer_retry"] >= 1


def test_point_exec_evict_storm_recompiles_and_serves(inst):
    chaos.arm("exec_evict:1:2")
    res = solve_tpu(inst, **KNOBS)
    _assert_valid(inst, res)
    assert chaos.snapshot()["fired"].get("exec_evict", 0) >= 1
    assert not res.stats.get("degraded")  # eviction is absorbed, not degraded


def test_point_pallas_fault_all_three_views_agree(inst):
    """The acceptance contract: stats field, trace mark, and metric
    counter agree for an injected Pallas fault."""
    before = ladder.snapshot()["pallas_to_xla"]
    chaos.arm("pallas_fault")
    res = solve_tpu(inst, trace=True, **KNOBS)
    _assert_valid(inst, res)
    stats_rungs = [r for r in res.stats["degradations"]
                   if r == "pallas_to_xla"]
    trace_rungs = [r for r in _degrade_rungs(res.stats["solve_report"])
                   if r == "pallas_to_xla"]
    metric_delta = ladder.snapshot()["pallas_to_xla"] - before
    assert len(stats_rungs) == len(trace_rungs) == metric_delta == 1
    # the /metrics rendering exposes the same count
    text = srv.render_metrics()
    assert 'kao_degradations_total{rung="pallas_to_xla"} 1' in text


def test_point_megachunk_fault_three_views_and_chunked_parity(inst):
    """A fault inside a fused megachunk dispatch steps down the
    ``megachunk_to_chunked`` rung and the per-chunk ladder finishes the
    solve. Three views agree (stats, trace mark, metric counter), and —
    because the drain re-enters at the first unfinished chunk with the
    carried state intact — the answer is bit-identical to a never-fused
    chunked solve."""
    kw = dict(seed=0, engine="sweep", batch=8, rounds=32,
              time_limit_s=3600.0, cert_min_savings_s=1e9)
    before = ladder.snapshot()["megachunk_to_chunked"]
    chaos.arm("megachunk_fault")
    res = solve_tpu(inst, trace=True, megachunk=2, **kw)
    _assert_valid(inst, res)
    assert chaos.snapshot()["fired"].get("megachunk_fault") == 1
    stats_rungs = [r for r in res.stats["degradations"]
                   if r == "megachunk_to_chunked"]
    trace_rungs = [r for r in _degrade_rungs(res.stats["solve_report"])
                   if r == "megachunk_to_chunked"]
    metric_delta = ladder.snapshot()["megachunk_to_chunked"] - before
    assert len(stats_rungs) == len(trace_rungs) == metric_delta == 1
    text = srv.render_metrics()
    assert 'kao_degradations_total{rung="megachunk_to_chunked"} 1' in text
    # drained-solve parity with the unfused chunked path
    chaos.disarm()
    base = solve_tpu(inst, **kw)
    assert np.array_equal(res.a, base.a)
    assert res.stats["score_curve"] == base.stats["score_curve"]


def test_point_nan_chunk_host_fallback_flagged_degraded(inst):
    chaos.arm("nan_chunk")
    res = solve_tpu(inst, **KNOBS)
    assert res.stats["engine"] == "host_fallback"
    assert res.stats["degraded"] == "anneal_to_construct"
    assert "anneal_to_construct" in res.stats["degradations"]
    # the degraded plan is still oracle-verified and usable
    assert res.stats["feasible"] and inst.is_feasible(res.a)
    assert res.objective == inst.preservation_weight(res.a)


def test_point_nan_chunk_sanitizer_armed_fails_loudly(inst):
    """Armed sanitizer means the operator asked for loud failure: the
    NaN must surface, not degrade (docs/ANALYSIS.md contract)."""
    from kafka_assignment_optimizer_tpu.analysis import sanitize

    chaos.arm("nan_chunk")
    sanitize.enable()
    try:
        with pytest.raises(FloatingPointError):
            solve_tpu(inst, **KNOBS)
    finally:
        sanitize.disable()
    assert ladder.snapshot()["anneal_to_construct"] == 0


def test_batch_lane_fallback_rungs_stay_per_lane():
    """An unstackable batch solves its lanes sequentially; a fault in
    ONE lane's solve must flag that lane's stats only — the sibling
    lane's plan was fully annealed and must not read as degraded."""
    from kafka_assignment_optimizer_tpu.solvers.tpu.engine import (
        solve_tpu_batch,
    )
    from kafka_assignment_optimizer_tpu.utils import gen

    def adv(seed, **overrides):
        kw = dict(n_brokers=32, n_topics_low=3, n_topics_high=3,
                  parts_per_topic=10, seed=seed)
        kw.update(overrides)
        sc = gen.adversarial(**kw)
        return build_instance(sc.current, sc.broker_list, sc.topology)

    a = adv(7)
    b = adv(7, n_brokers=48, n_topics_low=4, n_topics_high=4)
    chaos.arm("nan_chunk:1:1")  # fires once: in lane 0's solve only
    out = solve_tpu_batch([a, b], seeds=0, rounds=8, batch=8)
    assert chaos.snapshot()["fired"].get("nan_chunk", 0) == 1
    assert out[0].stats["degraded"] == "anneal_to_construct"
    assert "anneal_to_construct" in out[0].stats["degradations"]
    assert out[1].stats.get("lane_fallback")
    assert "anneal_to_construct" not in out[1].stats.get(
        "degradations", [])
    assert out[1].stats["feasible"]


def test_point_chunk_overrun_deadline_truncates(inst):
    # rounds=32 under a deadline cuts the sweep ladder into 4 chunks of
    # 8 (engine._build_chunks); every dispatch overruns by 0.5 s, so
    # the deadline gate must stop the ladder with chunks still left.
    # The demo instance certifies at the first boundary otherwise, so
    # the constructor race (precompile=True) and the boundary
    # certificate (cert_min_savings_s) are both disabled — this test is
    # about the deadline rung, not the early-stop shortcuts.
    chaos.arm("chunk_overrun:1:-1,delay=0.5")
    res = solve_tpu(inst, seed=0, batch=8, rounds=32,
                    steps_per_round=30, time_limit_s=0.8,
                    engine="sweep", precompile=True,
                    cert_min_savings_s=1e9)
    _assert_valid(inst, res)
    assert res.stats["timed_out"]
    assert "deadline_truncated" in res.stats.get("degradations", [])
    assert ladder.snapshot()["deadline_truncated"] >= 1


def test_point_checkpoint_write_failure_skips_not_dies(inst, tmp_path):
    ck = str(tmp_path / "plan.npz")
    chaos.arm("checkpoint_write")
    res = solve_tpu(inst, checkpoint=ck, **KNOBS)
    _assert_valid(inst, res)
    assert "checkpoint_skipped" in res.stats.get("degradations", [])
    import os

    assert not os.path.exists(ck)  # the write failed...
    chaos.disarm()
    res2 = solve_tpu(inst, checkpoint=ck, **KNOBS)
    _assert_valid(inst, res2)
    assert os.path.exists(ck)  # ...and the next solve persists again


def test_pipelined_sync_parity_under_mid_ladder_fault(inst):
    """A Pallas fault mid-ladder must leave pipelined and sync solves
    on the SAME trajectory (the drain-and-retry contract)."""
    chaos.arm("pallas_fault")
    a_pipe = solve_tpu(inst, pipeline=True, **KNOBS)
    chaos.arm("pallas_fault")  # re-arm: the first solve consumed it
    a_sync = solve_tpu(inst, pipeline=False, **KNOBS)
    assert np.array_equal(a_pipe.a, a_sync.a)
    assert a_pipe.objective == a_sync.objective


def test_disarmed_solves_bit_identical_after_chaos_cycle(inst):
    """Chaos disarmed = zero behavioural residue: a solve after an
    arm/fire/disarm cycle replays the never-armed trajectory bit for
    bit."""
    base = solve_tpu(inst, **KNOBS)
    chaos.arm("pallas_fault,nan_chunk:0.5,exec_evict:1:2")
    solve_tpu(inst, **KNOBS)
    chaos.disarm()
    again = solve_tpu(inst, **KNOBS)
    assert np.array_equal(base.a, again.a)
    assert base.objective == again.objective
    assert "degradations" not in again.stats


# --------------------------------------------------------------------------
# serve injection points + hardening
# --------------------------------------------------------------------------


def _payload(**extra):
    return {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "topology": "even-odd",
        "solver": "milp",
        **extra,
    }


def test_point_queue_overload_sheds_structured_503():
    chaos.arm("queue_overload")
    with pytest.raises(srv.ApiError) as ei:
        srv.handle_submit(_payload(), lock_wait_s=0.1)
    e = ei.value
    assert e.status == 503
    assert e.body_extra["reason"] == "queue_full"
    assert e.retry_after_s >= 1.0
    assert e.body_extra["queue_wait_s"] == srv._SOLVES.queue_wait_s
    with srv._METRICS_LOCK:
        assert srv._SHED_REASONS["queue_full"] >= 1
    # next request (chaos spent) proceeds normally
    out = srv.handle_submit(_payload())
    assert out["report"]["feasible"]


def test_point_worker_crash_respawns_and_retries():
    before = ladder.snapshot()["worker_restart"]
    chaos.arm("worker_crash")
    out = srv.handle_submit(_payload())
    assert out["report"]["feasible"]  # the retry delivered the plan
    assert ladder.snapshot()["worker_restart"] == before + 1
    # pool capacity was respawned, not lost: another request completes
    out2 = srv.handle_submit(_payload())
    assert out2["report"]["feasible"]


def test_point_slow_client_delays_but_serves(server_url_chaos):
    url = server_url_chaos
    chaos.arm("slow_client,delay=0.2")
    t0 = time.perf_counter()
    status, body, headers = _post(url, "/submit", _payload())
    assert time.perf_counter() - t0 >= 0.2
    assert status == 200 and body["report"]["feasible"]


def test_deadline_field_validation():
    for bad in (0, -1, "fast", True):
        with pytest.raises(srv.ApiError) as ei:
            srv.handle_submit(_payload(deadline_s=bad))
        assert ei.value.status == 400


def test_deadline_exhausted_sheds_before_solving():
    with pytest.raises(srv.ApiError) as ei:
        srv.handle_submit(_payload(solver="tpu", deadline_s=1e-6))
    e = ei.value
    assert e.status == 503 and e.body_extra["reason"] == "deadline"
    with srv._METRICS_LOCK:
        assert srv._SHED_REASONS["deadline"] >= 1


def test_default_deadline_applied_and_capped(monkeypatch):
    monkeypatch.setitem(srv.RESILIENCE, "default_deadline_s", 45.0)
    seen = {}
    import kafka_assignment_optimizer_tpu.serve as serve_mod

    real = serve_mod.optimize

    def spy(*a, **kw):
        seen.update(kw)
        return real(*a, **kw)

    monkeypatch.setattr(serve_mod, "optimize", spy)
    srv.handle_submit(_payload())
    # the solve ran on the REMAINING deadline, not the full --max-solve-s
    assert 0 < seen["time_limit_s"] <= 45.0


def test_auto_resolves_to_concrete_solver_for_gates(inst):
    """The per-bucket gates (breaker, checkpoint resume, coalescing)
    key on the solver that will ACTUALLY run, so "auto" must resolve
    deterministically from the instance size."""
    from kafka_assignment_optimizer_tpu.solvers.base import (
        available_solvers,
        resolve_solver,
    )

    assert resolve_solver("milp", inst) == "milp"   # passthrough
    assert resolve_solver("auto", inst) == "milp"   # demo: tiny space

    class _Big:  # only the size fields participate in resolution
        num_brokers, num_parts = 64, 400            # 51200 vars

    expect = "tpu" if "tpu" in available_solvers() else "milp"
    assert resolve_solver("auto", _Big()) == expect


def test_auto_request_shares_breaker_key_with_resolved_solver(monkeypatch):
    """Defaulted ("auto") requests trip/see the SAME circuit as the
    solver they resolve to — not one shared ("solver", "auto") key a
    single pathological cluster could open for the whole fleet."""
    import kafka_assignment_optimizer_tpu.serve as serve_mod

    srv._BREAKER.configure(threshold=2, cooldown_s=30.0)

    def boom(*a, **kw):
        raise RuntimeError("compile exploded")

    monkeypatch.setattr(serve_mod, "optimize", boom)
    auto = _payload()
    del auto["solver"]  # schema default: "auto" -> milp on the demo
    for _ in range(2):
        with pytest.raises(srv.ApiError) as ei:
            srv.handle_submit(auto)
        assert ei.value.status == 500
    # the circuit those defaulted requests opened sheds explicit milp
    # traffic too: one resolved key, not two parallel failure counters
    with pytest.raises(srv.ApiError) as ei:
        srv.handle_submit(_payload())
    e = ei.value
    assert e.status == 503 and e.body_extra["reason"] == "circuit_open"


def test_batch_job_sheds_expired_members_and_threads_remaining(monkeypatch):
    """Coalesced-lane deadline contract: a member whose request
    deadline expired while the batch was queued sheds with the same
    503 "deadline" the single path returns, and the batched solve runs
    only the live lanes — on the tightest REMAINING member window, not
    the full time_limit_s."""
    import kafka_assignment_optimizer_tpu.api as api_mod

    class _Fake:
        class _A:
            @staticmethod
            def to_dict():
                return {"stub": True}

        assignment = _A()

        @staticmethod
        def report():
            return {"feasible": True}

    seen = {}

    def fake_batch(currents, instances, seeds, **kw):
        seen["lanes"] = len(instances)
        seen.update(kw)
        return [_Fake()]

    monkeypatch.setattr(api_mod, "optimize_batch", fake_batch)
    live = {"current": None, "instance": object(), "seed": 0,
            "trace_id": None, "budget": rbudget.Budget(30.0),
            "options": {"time_limit_s": 60.0}}
    dead = dict(live, budget=rbudget.Budget(1e-9))
    time.sleep(0.01)  # the dead member's budget expires
    outs = srv._run_batch_job([dead, live])
    assert isinstance(outs[0], srv.ApiError)
    assert outs[0].status == 503
    assert outs[0].body_extra["reason"] == "deadline"
    assert outs[1] == {"assignment": {"stub": True},
                       "report": {"feasible": True}}
    assert seen["lanes"] == 1
    assert seen["time_limit_s"] <= 30.0
    with srv._METRICS_LOCK:
        assert srv._SHED_REASONS["deadline"] >= 1


def test_circuit_breaker_opens_after_repeated_failures(monkeypatch):
    import kafka_assignment_optimizer_tpu.serve as serve_mod

    srv._BREAKER.configure(threshold=2, cooldown_s=30.0)

    def boom(*a, **kw):
        raise RuntimeError("compile exploded")

    monkeypatch.setattr(serve_mod, "optimize", boom)
    for _ in range(2):
        with pytest.raises(srv.ApiError) as ei:
            srv.handle_submit(_payload())
        assert ei.value.status == 500
    # circuit is open: the next request sheds WITHOUT calling optimize
    monkeypatch.setattr(serve_mod, "optimize",
                        lambda *a, **kw: pytest.fail("must not dispatch"))
    with pytest.raises(srv.ApiError) as ei:
        srv.handle_submit(_payload())
    e = ei.value
    assert e.status == 503 and e.body_extra["reason"] == "circuit_open"
    assert e.retry_after_s > 0
    assert srv._BREAKER.snapshot()["open"] == 1


def test_checkpoint_dir_auto_resume(tmp_path, monkeypatch):
    """--checkpoint-dir: a repeated solve of the same cluster finds the
    fingerprint-keyed checkpoint of the first (crash-safe resume)."""
    import os

    monkeypatch.setitem(srv.RESILIENCE, "checkpoint_dir", str(tmp_path))
    out = srv.handle_submit(_payload(
        solver="tpu",
        options={"rounds": 4, "steps_per_round": 60, "batch": 8},
    ))
    assert out["report"]["feasible"]
    files = os.listdir(tmp_path)
    assert len(files) == 1 and files[0].endswith(".npz")
    out2 = srv.handle_submit(_payload(
        solver="tpu",
        options={"rounds": 4, "steps_per_round": 60, "batch": 8},
    ))
    assert out2["report"]["feasible"]
    assert os.listdir(tmp_path) == files  # same cluster, same key


# --------------------------------------------------------------------------
# HTTP surface: Retry-After + metrics/healthz exposition
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def server_url_chaos():
    s = srv.make_server(port=0)
    t = threading.Thread(target=s.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{s.server_address[1]}"
    s.shutdown()
    s.server_close()


def _post(url, path, payload):
    import json
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_http_503_carries_retry_after_header(server_url_chaos):
    chaos.arm("queue_overload")
    status, body, headers = _post(server_url_chaos, "/submit", _payload())
    assert status == 503
    assert body["reason"] == "queue_full"
    assert body["retry_after_s"] > 0
    assert int(headers["Retry-After"]) >= 1


def test_healthz_exposes_resilience_state():
    h = srv.handle_healthz()
    r = h["resilience"]
    assert set(r["degradations"]) == set(ladder.RUNGS)
    assert r["chaos"]["armed"] == 0
    assert {"open", "tracked", "trips_total"} <= set(r["breaker"])
    assert r["queue_wait_s"] == srv._SOLVES.queue_wait_s


def test_metrics_exposition_valid_with_resilience_families():
    from tests.test_metrics_format import validate_prometheus

    ladder.note_rung("aot_to_jit")
    text = srv.render_metrics()
    validate_prometheus(text)
    assert 'kao_shed_total{reason="queue_full"}' in text
    assert 'kao_degradations_total{rung="aot_to_jit"} 1' in text
    assert "kao_breaker_open_keys" in text
    assert "kao_chaos_armed 0" in text


# --------------------------------------------------------------------------
# KAO108: chaos hooks must never reach traced bodies
# --------------------------------------------------------------------------


def test_kao108_flags_chaos_in_traced_bodies():
    from kafka_assignment_optimizer_tpu.analysis.rules_ast import (
        lint_source,
    )

    bad = (
        "import jax\n"
        "from kafka_assignment_optimizer_tpu.resilience import (\n"
        "    chaos as _chaos, ladder as _ladder)\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    _chaos.raise_if('pallas_fault')\n"
        "    return x + 1\n"
        "def make_sweep_stepper_fn():\n"
        "    def body(state):\n"
        "        _ladder.note_rung('pallas_to_xla')\n"
        "        return state\n"
        "    return body\n"
    )
    hits = [f for f in lint_source(bad, "fx.py") if f.rule == "KAO108"]
    assert len(hits) == 2
    good = (
        "from kafka_assignment_optimizer_tpu.resilience import (\n"
        "    chaos as _chaos)\n"
        "def dispatch(i):\n"
        "    _chaos.raise_if('pallas_fault')\n"
        "    return i\n"
    )
    assert not [f for f in lint_source(good, "g.py")
                if f.rule == "KAO108"]


def test_repo_is_kao108_clean():
    """The real tree's chaos hooks all sit at host-side dispatch sites."""
    from kafka_assignment_optimizer_tpu import analysis

    findings = [
        f for f in analysis.lint_paths()  # default: the package tree
        if f.rule == "KAO108"
    ]
    assert findings == []
