"""Bucket-affinity fleet router tests (docs/FLEET.md, ISSUE 14).

The router is pure stdlib + host-side bucket math, so these tests run
against FAKE workers — tiny in-process HTTP servers scripted to answer
/healthz, /submit, /warmup and /clusters like a serve worker would —
and never import jax (the one subprocess test pins that the router
module itself doesn't either). Worker-integration behavior (real
solves through a real fleet) lives in the soak tier and
``bench.py --fleet-bench``.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kafka_assignment_optimizer_tpu.fleet import affinity
from kafka_assignment_optimizer_tpu.fleet.health import FleetTracker
from kafka_assignment_optimizer_tpu.fleet.router import (
    Router,
    make_router_server,
    render_router_metrics,
)
from kafka_assignment_optimizer_tpu.models.cluster import (
    Assignment,
    Topology,
    demo_assignment,
    parse_broker_list,
)


# --------------------------------------------------------------------------
# host-side bucket key parity with the serve/build_instance path
# --------------------------------------------------------------------------


def _serve_side_key(payload):
    """The key serve.handle_submit computes: build the real instance."""
    from kafka_assignment_optimizer_tpu.models.instance import (
        build_instance,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu import bucket

    current = Assignment.from_dict(payload["assignment"])
    spec = payload["brokers"]
    brokers = (parse_broker_list(spec) if isinstance(spec, str)
               else list(spec))
    all_ids = sorted(set(brokers) | set(current.broker_ids()))
    topo_spec = payload.get("topology")
    if topo_spec is None:
        topo = None
    elif topo_spec == "even-odd":
        topo = Topology.even_odd(all_ids)
    else:
        topo = Topology.from_dict(topo_spec)
    inst = build_instance(current, brokers, topo, payload.get("rf"))
    return (inst.num_brokers, inst.num_racks, *bucket.bucket_shape(inst))


@pytest.mark.parametrize("mutate", [
    {},                                        # demo verbatim
    {"topology": None},                        # single rack
    {"brokers": list(range(12))},              # list form, shrunk
    {"rf": 2},                                 # int rf override
    {"rf": {"x.y.z.t": 4}},                    # per-topic rf
    {"topology": {str(b): f"r{b % 3}" for b in range(19)}},
])
def test_bucket_key_matches_build_instance(mutate):
    """The router's host-side key must equal the key the worker
    computes when it builds the instance — otherwise affinity routes
    to the wrong warmth."""
    payload = {
        "assignment": demo_assignment().to_dict(),
        "brokers": "0-18",
        "topology": "even-odd",
        **mutate,
    }
    assert affinity.bucket_key_of(payload) == _serve_side_key(payload)


def test_bucket_key_malformed_is_none():
    for bad in (
        {},                                           # nothing
        {"assignment": "nope", "brokers": "0-3"},     # bad assignment
        {"assignment": demo_assignment().to_dict()},  # no brokers
        {"assignment": demo_assignment().to_dict(),
         "brokers": "0-18", "rf": 99},                # rf > brokers
        {"assignment": demo_assignment().to_dict(),
         "brokers": "0-18", "topology": 7},           # bad topology
    ):
        assert affinity.bucket_key_of(bad) is None


# --------------------------------------------------------------------------
# rendezvous stability + warmth bias
# --------------------------------------------------------------------------


def test_rendezvous_join_leave_moves_only_owned_keys():
    """Removing a worker must re-home ONLY the keys it owned; adding
    it back restores the original owners exactly (the property that
    makes affinity stable under fleet churn)."""
    workers = [f"http://w{i}" for i in range(5)]
    keys = [(19, 2, p, 3) for p in (32, 48, 72, 112, 168, 256, 384)]
    owner_before = {k: affinity.rendezvous_rank(k, workers)[0]
                    for k in keys}
    gone = workers[2]
    rest = [w for w in workers if w != gone]
    for k in keys:
        after = affinity.rendezvous_rank(k, rest)[0]
        if owner_before[k] != gone:
            assert after == owner_before[k], (k, after)
        else:
            # the orphaned key lands on its previous runner-up
            assert after == affinity.rendezvous_rank(k, workers)[1]
    # rejoin restores every original owner
    assert {k: affinity.rendezvous_rank(k, workers)[0]
            for k in keys} == owner_before


def test_rank_workers_warm_bias_is_stable():
    workers = [f"http://w{i}" for i in range(4)]
    key = (19, 2, 32, 3)
    base = affinity.rendezvous_rank(key, workers)
    warm_worker = base[-1]  # the rendezvous LOSER is the warm one
    ranked = affinity.rank_workers(key, workers,
                                   {warm_worker: {key}})
    assert ranked[0] == warm_worker
    # cold group keeps rendezvous order
    assert ranked[1:] == [w for w in base if w != warm_worker]
    # no ledger -> pure rendezvous
    assert affinity.rank_workers(key, workers, {}) == base


def test_router_module_never_imports_jax():
    """The router front process must boot without jax (no backend
    init, no accelerator deps) — docs/FLEET.md contract."""
    code = (
        "import sys;"
        "import kafka_assignment_optimizer_tpu.fleet.router;"
        "sys.exit(1 if 'jax' in sys.modules else 0)"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-500:]


# --------------------------------------------------------------------------
# fake workers
# --------------------------------------------------------------------------


class _FakeWorker:
    """A scripted serve-worker stand-in: answers /healthz with a warm-
    bucket ledger and /submit//warmup//clusters per its ``mode``."""

    def __init__(self, warm=(), mode="ok", retry_after_s=0.2,
                 solve_s=0.0, shed_first=0):
        self.warm = [list(k) for k in warm]
        self.mode = mode
        self.retry_after_s = retry_after_s
        self.solve_s = solve_s
        self.shed_first = shed_first  # shed the first N posts, then ok
        self.requests: list = []  # (path, payload)
        self._lock = threading.Lock()
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _json(self, status, obj, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/healthz"):
                    self._json(200, {
                        "status": "ok",
                        "cache": {"warm_buckets": fake.warm},
                        "queue": {"depth": 0},
                    })
                elif self.path.startswith("/clusters"):
                    self._json(200, {"clusters": {}, "worker": fake.url})
                else:
                    self._json(404, {"error": "nope"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                with fake._lock:
                    fake.requests.append((self.path, payload))
                    n_seen = len(fake.requests)
                if fake.mode == "shed" or n_seen <= fake.shed_first:
                    self._json(503, {
                        "error": "queue full",
                        "reason": "queue_full",
                        "retry_after_s": fake.retry_after_s,
                        "worker": {"host": "fake", "pid": 1},
                    }, headers={"Retry-After": "1"})
                    return
                if fake.solve_s:
                    time.sleep(fake.solve_s)
                if self.path == "/warmup":
                    self._json(200, {"warmed": [
                        {"shape": sh, "compiles": 1,
                         "persistent": {"hits": 0, "misses": 1}}
                        for sh in payload.get("shapes", [])
                    ]})
                    return
                self._json(200, {
                    "worker": fake.url,
                    "path": self.path,
                    "epoch": payload.get("epoch"),
                    "report": {"feasible": True},
                })

        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.url = f"http://127.0.0.1:{self.srv.server_address[1]}"
        self._thread = threading.Thread(target=self.srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def posts(self, path_prefix="/"):
        with self._lock:
            return [(p, b) for p, b in self.requests
                    if p.startswith(path_prefix)]

    def kill(self):
        self.srv.shutdown()
        self.srv.server_close()


def _make_router(workers, **kw):
    tracker = FleetTracker([w.url for w in workers], interval_s=3600,
                           timeout_s=2.0)
    tracker.poll_once()
    router = Router(tracker, lock_wait_s=kw.pop("lock_wait_s", 3.0),
                    solve_timeout_s=10.0, connect_timeout_s=2.0, **kw)
    srv = make_router_server("127.0.0.1", 0, router)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    return router, srv, url


def _post(url, path, payload, timeout=15.0):
    req = urllib.request.Request(
        f"{url}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


DEMO_PAYLOAD = {
    "assignment": demo_assignment().to_dict(),
    "brokers": "0-18",
    "topology": "even-odd",
    "solver": "tpu",
}
DEMO_KEY = affinity.bucket_key_of(DEMO_PAYLOAD)


def test_router_routes_to_warm_worker():
    """A keyed /submit goes to the worker whose /healthz ledger
    reports the bucket warm — even when rendezvous alone would pick
    another — and the affinity counters record the hit."""
    # find which worker rendezvous would pick, then warm the OTHER
    a, b = _FakeWorker(), _FakeWorker()
    try:
        cold_first = affinity.rendezvous_rank(
            DEMO_KEY, [a.url, b.url])[0]
        warm_w = b if cold_first == a.url else a
        warm_w.warm = [list(DEMO_KEY)]
        router, srv, url = _make_router([a, b])
        try:
            router.tracker.poll_once()  # pick up the ledger
            status, body = _post(url, "/submit", DEMO_PAYLOAD)
            assert status == 200
            assert body["worker"] == warm_w.url
            snap = router.snapshot()
            assert snap["counters"]["affinity_hits_total"] == 1
            assert snap["routing"]["affinity_rate"] == 1.0
        finally:
            srv.shutdown()
            srv.server_close()
    finally:
        a.kill()
        b.kill()


def test_router_failover_on_killed_worker_zero_drops():
    """SIGKILL-equivalent (listener gone): every request still
    completes via the surviving worker; the dead worker leaves the
    routing set and the retry counter records the failovers."""
    a, b = _FakeWorker(warm=[DEMO_KEY]), _FakeWorker(warm=[DEMO_KEY])
    router, srv, url = _make_router([a, b])
    # kill the worker affinity would pick first
    ranked = affinity.rank_workers(
        DEMO_KEY, [a.url, b.url], router.tracker.warm_map())
    dead, alive = (a, b) if ranked[0] == a.url else (b, a)
    try:
        dead.kill()
        results = []

        def client(i):
            results.append(_post(url, "/submit", DEMO_PAYLOAD))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(results) == 6
        assert all(s == 200 for s, _ in results), results
        assert all(body["worker"] == alive.url for _, body in results)
        snap = router.snapshot()
        assert snap["counters"]["retries_total"]["connect_fail"] >= 1
        assert dead.url not in snap["fleet"]["live"]
        assert alive.url in snap["fleet"]["live"]
    finally:
        srv.shutdown()
        srv.server_close()
        alive.kill()  # `dead` was killed mid-test


def test_router_failover_on_shed_honors_retry_after():
    """A 503 shed fails over to the next worker AND starts that
    worker's cooldown: follow-up requests inside the Retry-After
    window go straight to the healthy worker without re-knocking."""
    # warm ONLY the shedding worker so it is deterministically
    # first-ranked and the failover path is what serves the request
    a = _FakeWorker(warm=[DEMO_KEY], mode="shed", retry_after_s=30.0)
    b = _FakeWorker()
    router, srv, url = _make_router([a, b])
    try:
        for _ in range(3):
            status, body = _post(url, "/submit", DEMO_PAYLOAD)
            assert status == 200
            assert body["worker"] == b.url
        # the shedding worker was knocked exactly once: after its
        # Retry-After promise the router must not re-send traffic
        assert len(a.posts("/submit")) == 1
        snap = router.snapshot()
        assert snap["counters"]["retries_total"]["shed"] == 1
    finally:
        srv.shutdown()
        srv.server_close()
        a.kill()
        b.kill()


def test_router_waits_out_short_cooldown_instead_of_shedding():
    """A short Retry-After inside the request's wait budget is slept
    out by the ROUTER (microsecond-precision float), not surfaced to
    the client whose header-level backoff floor is a whole second —
    the request completes on the same worker after its promise
    expires."""
    a = _FakeWorker(retry_after_s=0.25, shed_first=1)
    router, srv, url = _make_router([a], lock_wait_s=5.0)
    try:
        t0 = time.perf_counter()
        status, body = _post(url, "/submit", DEMO_PAYLOAD)
        dt = time.perf_counter() - t0
        assert status == 200
        assert body["worker"] == a.url
        assert 0.2 <= dt < 2.0, dt  # slept the promise, no 1 s floor
        snap = router.snapshot()
        assert snap["counters"]["retries_total"]["cooldown_wait"] >= 1
        assert snap["counters"]["exhausted_total"] == 0
    finally:
        srv.shutdown()
        srv.server_close()
        a.kill()


def test_router_exhausted_returns_503_with_retry_after():
    a = _FakeWorker(mode="shed", retry_after_s=20.0)
    router, srv, url = _make_router([a], lock_wait_s=0.5)
    try:
        status, body = _post(url, "/submit", DEMO_PAYLOAD)
        assert status == 503
        assert body["reason"] == "fleet_exhausted"
        assert body["retry_after_s"] > 0
        assert router.snapshot()["counters"]["exhausted_total"] == 1
    finally:
        srv.shutdown()
        srv.server_close()
        a.kill()


def test_watch_cluster_stickiness_single_writer():
    """Every command for one cluster lands on ONE worker (epoch
    fencing sees a single writer) regardless of warmth; different
    clusters may own different workers; a dead owner hands the cluster
    to the rendezvous runner-up."""
    a, b = _FakeWorker(warm=[DEMO_KEY]), _FakeWorker(warm=[DEMO_KEY])
    router, srv, url = _make_router([a, b])
    try:
        cids = [f"c{i}" for i in range(8)]
        for cid in cids:
            for epoch in (1, 2, 3):
                status, body = _post(
                    url, f"/clusters/{cid}/events",
                    {"type": "bootstrap", "epoch": epoch},
                )
                assert status == 200
        by_worker = {w.url: {p.split("/")[2] for p, _ in
                             w.posts("/clusters/")}
                     for w in (a, b)}
        # one writer per cluster: no cluster id on both workers
        assert not (by_worker[a.url] & by_worker[b.url])
        # stickiness matches the rendezvous owner the router promises
        for cid in cids:
            owner = affinity.rendezvous_rank(
                ("cluster", cid), [a.url, b.url])[0]
            assert cid in by_worker[owner]
        assert router.snapshot()["counters"]["sticky_total"] == 24
        # failover: kill a's listener; its clusters re-home to b
        a_cluster = next(iter(by_worker[a.url]))
        a.kill()
        status, body = _post(
            url, f"/clusters/{a_cluster}/events",
            {"type": "bootstrap", "epoch": 9},
        )
        assert status == 200
        assert body["worker"] == b.url
    finally:
        srv.shutdown()
        srv.server_close()
        b.kill()


def test_warmup_partition_each_bucket_once_fleetwide():
    """The router partitions warmup shapes by bucket owner (phase 1 —
    each bucket compiles exactly once fleet-wide) and spreads the rest
    to every other worker (phase 2 — shared-cache pulls)."""
    a, b = _FakeWorker(), _FakeWorker()
    router, srv, url = _make_router([a, b])
    try:
        shapes = [
            {"brokers": 12, "partitions": 64, "rf": 3, "racks": 4},
            {"brokers": 12, "partitions": 200, "rf": 3, "racks": 4},
            {"brokers": 19, "partitions": 64, "rf": 3, "racks": 2},
        ]
        status, out = _post(url, "/warmup", {"shapes": shapes})
        assert status == 200
        # phase 1: the shape partition covers every shape exactly once
        part = out["partition"]
        assert sorted(
            (sh["brokers"], sh["partitions"])
            for shs in part.values() for sh in shs
        ) == sorted((sh["brokers"], sh["partitions"]) for sh in shapes)
        # and each went to its rendezvous owner over the live set
        for worker_url, shs in part.items():
            for sh in shs:
                key = affinity.shape_key(sh["brokers"],
                                         sh["partitions"], sh["rf"],
                                         sh["racks"])
                assert affinity.rendezvous_rank(
                    key, [a.url, b.url])[0] == worker_url
        # phase 2: every worker warms exactly the shapes it does NOT
        # own (the shared-compile-cache spread)
        for w in (a, b):
            own = {(sh["brokers"], sh["partitions"])
                   for sh in part.get(w.url, [])}
            posted = [
                (sh["brokers"], sh["partitions"])
                for _, body in w.posts("/warmup")
                for sh in body.get("shapes", [])
            ]
            assert sorted(posted) == sorted(
                [(sh["brokers"], sh["partitions"]) for sh in shapes]
            ), (w.url, posted)  # own (phase1) + others (phase2) = all
        # the fake rows report 1 persistent miss per shape, so the
        # accounting must add up: 3 owned + 3 spread
        assert out["fresh_compiles"] == 3
        assert out["spread_fresh_compiles"] == 3
        # spread="owners" skips phase 2
        status, out2 = _post(url, "/warmup",
                             {"shapes": shapes, "spread": "owners"})
        assert status == 200 and out2["phase2"] == {}
    finally:
        srv.shutdown()
        srv.server_close()
        a.kill()
        b.kill()


def test_warmup_error_reads_as_unproven_not_zero():
    """A worker failing its warmup must surface in ``errors`` AND null
    out the phase's fresh-compile count — a failed spread can never be
    mistaken for the '0 fresh compiles' shared-cache proof (the
    acceptance gates compare against 0; None != 0)."""
    a, b = _FakeWorker(mode="shed"), _FakeWorker(mode="shed")
    router, srv, url = _make_router([a, b])
    try:
        status, out = _post(url, "/warmup", {"shapes": [
            {"brokers": 12, "partitions": 64, "rf": 3, "racks": 4},
            {"brokers": 12, "partitions": 200, "rf": 3, "racks": 4},
        ]})
        assert status == 200
        assert out["errors"], out
        assert out["fresh_compiles"] is None
        assert out["spread_fresh_compiles"] is None
        assert out["spread_fresh_compiles"] != 0  # the gate's read
    finally:
        srv.shutdown()
        srv.server_close()
        a.kill()
        b.kill()


def test_router_healthz_and_metrics_surfaces():
    a = _FakeWorker(warm=[DEMO_KEY])
    router, srv, url = _make_router([a])
    try:
        _post(url, "/submit", DEMO_PAYLOAD)
        with urllib.request.urlopen(f"{url}/healthz",
                                    timeout=10) as resp:
            hz = json.loads(resp.read())
        assert hz["role"] == "router"
        assert hz["fleet"]["workers"][a.url]["warm_buckets"] == [
            list(DEMO_KEY)
        ]
        assert hz["routing"]["affinity_rate"] == 1.0
        with urllib.request.urlopen(f"{url}/metrics",
                                    timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith(
                "text/plain")
            text = resp.read().decode()
        from tests.test_metrics_format import validate_prometheus

        samples = validate_prometheus(text)
        names = {n for n, _ in samples}
        assert {"kao_router_requests_total",
                "kao_router_affinity_hits_total",
                "kao_router_affinity_rate",
                "kao_router_retries_total",
                "kao_router_worker_up",
                "kao_router_workers"} <= names, names
    finally:
        srv.shutdown()
        srv.server_close()
        a.kill()


def test_hedge_fires_after_window_and_secondary_wins():
    """A deadline-carrying /submit whose primary stalls past the hedge
    window gets a duplicate on the next-ranked worker; the faster
    answer wins and the hedge counters record it."""
    # primary: slow (1.5 s); secondary: instant. Warm ONLY the slow
    # one so it is deterministically ranked first.
    slow = _FakeWorker(warm=[DEMO_KEY], solve_s=1.5)
    fast = _FakeWorker()
    router, srv, url = _make_router([slow, fast], hedge_ms=100.0,
                                    hedge_budget=2)
    try:
        t0 = time.perf_counter()
        status, body = _post(url, "/submit",
                             {**DEMO_PAYLOAD, "deadline_s": 30})
        dt = time.perf_counter() - t0
        assert status == 200
        assert body["worker"] == fast.url  # the hedge won
        assert dt < 1.4, dt  # did not wait out the slow primary
        snap = router.snapshot()
        assert snap["counters"]["hedges_total"] == 1
        assert snap["counters"]["hedge_wins_total"] == 1
        # without a deadline the same request does NOT hedge
        status, body = _post(url, "/submit", DEMO_PAYLOAD)
        assert status == 200 and body["worker"] == slow.url
        assert router.snapshot()["counters"]["hedges_total"] == 1
    finally:
        srv.shutdown()
        srv.server_close()
        slow.kill()
        fast.kill()


def test_hedge_attribution_survives_unscheduled_hedge_thread():
    """Both attempt span IDs reach the envelope even when the primary
    retires before the hedge thread ever runs its attempt: span IDs
    are stamped at launch() time, before Thread.start(), so the
    winner's merge can never observe a half-born race."""
    slow = _FakeWorker(warm=[DEMO_KEY], solve_s=0.4)
    fast = _FakeWorker()
    router, srv, url = _make_router([slow, fast], hedge_ms=50.0,
                                    hedge_budget=2)
    gate = threading.Event()
    real = type(router)._attempt_one

    def gated(*a, **kw):
        if kw.get("hedge"):
            # deterministically reproduce the race: the hedge thread
            # is launched but its attempt body does not run until the
            # primary has already won and merged
            gate.wait(5.0)
        return real(router, *a, **kw)

    router._attempt_one = gated
    try:
        status, body = _post(url, "/submit",
                             {**DEMO_PAYLOAD, "deadline_s": 30})
        gate.set()
        assert status == 200
        route = body["route"]
        assert route["worker"] == slow.url  # the gated hedge lost
        assert route["answered_by_hedge"] is False
        assert route["hedge_won"] is False
        # the regression: pre-fix the hedge span ID was written inside
        # the hedge thread's attempt, so it was absent here
        assert route.get("primary_span_id")
        assert route.get("hedge_span_id")
        assert route["primary_span_id"] != route["hedge_span_id"]
        assert router.snapshot()["counters"]["hedges_total"] == 1
    finally:
        gate.set()
        srv.shutdown()
        srv.server_close()
        slow.kill()
        fast.kill()
