"""Shared persistent compile cache (docs/FLEET.md, ISSUE 14
satellite): one ``KAO_COMPILE_CACHE`` dir turns one worker's cold XLA
compile into every other worker's disk hit — the mechanism that lets
fleet warmup compile each bucket exactly once fleet-wide.

The cross-process test here is the satellite's named proof: a second
worker process pointed at the same cache dir reports ZERO fresh
compiles (persistent-cache misses) for a bucket the first process
already compiled, while its hit counter — surfaced in /healthz
"cache" via ``utils.platform.compile_cache_stats`` — accounts for
every executable it pulled from disk.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from kafka_assignment_optimizer_tpu.utils import platform as kplat


def test_compile_cache_dir_env_resolution(monkeypatch):
    monkeypatch.delenv("KAO_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("KAO_JIT_CACHE", raising=False)
    # default: under the XDG cache home
    monkeypatch.setenv("XDG_CACHE_HOME", "/tmp/xdg-probe")
    assert kplat.compile_cache_dir() == \
        "/tmp/xdg-probe/kafka_assignment_optimizer_tpu/jit"
    # the fleet spelling wins over the legacy one
    monkeypatch.setenv("KAO_JIT_CACHE", "/tmp/legacy")
    assert kplat.compile_cache_dir() == "/tmp/legacy"
    monkeypatch.setenv("KAO_COMPILE_CACHE", "/tmp/fleet")
    assert kplat.compile_cache_dir() == "/tmp/fleet"
    # off disables entirely, in either spelling
    monkeypatch.setenv("KAO_COMPILE_CACHE", "off")
    assert kplat.compile_cache_dir() is None
    monkeypatch.delenv("KAO_COMPILE_CACHE")
    monkeypatch.setenv("KAO_JIT_CACHE", "none")
    assert kplat.compile_cache_dir() is None


def test_compile_cache_stats_shape_without_jax_config():
    snap = kplat.compile_cache_stats()
    assert set(snap) == {"dir", "enabled", "hits", "misses"}
    assert isinstance(snap["hits"], int)
    assert isinstance(snap["misses"], int)


_SOLVE_SNIPPET = r"""
import json, sys
from kafka_assignment_optimizer_tpu import optimize
from kafka_assignment_optimizer_tpu.models.cluster import (
    demo_assignment, demo_broker_list, demo_topology,
)
from kafka_assignment_optimizer_tpu.solvers.tpu.bucket import STATS
from kafka_assignment_optimizer_tpu.utils.platform import (
    compile_cache_stats,
)

res = optimize(demo_assignment(), demo_broker_list(), demo_topology(),
               solver="tpu", engine="sweep", batch=8, sweeps=16,
               seed=0)
assert res.report()["feasible"], res.report()
print("STATS " + json.dumps({
    "persistent": compile_cache_stats(),
    "warm_buckets": STATS.seen(),
    "fresh_compiles": compile_cache_stats()["misses"],
}))
"""


def _run_worker(cache_dir: str) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "KAO_COMPILE_CACHE": cache_dir,
        # demo-bucket executables compile in well under the default
        # 0.5 s persist threshold on CPU; the fleet knob lowers it so
        # small buckets share warmth too
        "KAO_COMPILE_CACHE_MIN_S": "0",
    })
    proc = subprocess.run(
        [sys.executable, "-c", _SOLVE_SNIPPET], env=env,
        capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    line = next(ln for ln in proc.stdout.splitlines()
                if ln.startswith("STATS "))
    return json.loads(line[len("STATS "):])


@pytest.mark.soak
@pytest.mark.slow  # ~15 s (spawns a second python); nightly — the
# fleet-warmup soak step proves the same 0-fresh-compile contract
# across REAL worker processes every night.
def test_second_process_pays_zero_fresh_compiles(tmp_path):
    """The satellite's acceptance proof: worker 1 cold-compiles the
    demo bucket into the shared dir; worker 2 — a genuinely fresh
    process — solves the same bucket with 0 persistent-cache misses
    (no fresh XLA compiles), all hits."""
    cache = str(tmp_path / "shared-jit")
    first = _run_worker(cache)
    assert first["persistent"]["enabled"], first
    assert first["fresh_compiles"] > 0, first  # cold: real compiles
    assert first["persistent"]["hits"] == 0, first
    second = _run_worker(cache)
    assert second["fresh_compiles"] == 0, second  # every one a disk hit
    assert second["persistent"]["hits"] > 0, second
    # both workers report the SAME bucket warm — the affinity ledger
    # the router reads agrees across the fleet
    assert second["warm_buckets"] == first["warm_buckets"]
    assert first["warm_buckets"], first


def test_healthz_cache_surfaces_persistent_counters():
    """/healthz "cache" carries the persistent hit/miss counters and
    the warm-bucket affinity ledger (serve-side fields the router and
    the fleet-warmup accounting read)."""
    pytest.importorskip("jax")
    from kafka_assignment_optimizer_tpu import serve as srv

    hz = srv.handle_healthz()
    cache = hz["cache"]
    assert set(cache["persistent_cache"]) == {"dir", "enabled",
                                              "hits", "misses"}
    assert isinstance(cache["warm_buckets"], list)
    for k in cache["warm_buckets"]:
        assert len(k) == 4 and all(isinstance(x, int) for x in k)
