"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh so every
multi-chip code path (shard_map, psum over ICI) runs in CI without TPU
hardware — the analogue of the reference-style 'test multi-node without a
cluster' strategy (SURVEY.md §4.5)."""

import os

# KAO_LSAN=1 arms the runtime lock sanitizer BEFORE any project module
# creates its locks (module-level Lock() sites bind at import), so the
# whole tier-1 suite doubles as a lock-order/hold-budget sanitizer run
# (docs/ANALYSIS.md "Runtime lock sanitizer").
_LSAN = None
if os.environ.get("KAO_LSAN", "").strip().lower() in (
    "1", "true", "yes", "on"
):
    from kafka_assignment_optimizer_tpu.analysis import lsan as _LSAN

    _LSAN.install()

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# a site plugin may have force-registered an accelerator platform and
# overridden the env var programmatically; the config update re-selects
# CPU as long as no backend has been initialized yet — assert loudly
# rather than letting the suite quietly run on the wrong platform
jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu" and jax.device_count() == 8, (
    f"tests need the 8-device CPU mesh, got {jax.device_count()} "
    f"{jax.default_backend()} device(s); a plugin initialized JAX first"
)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from kafka_assignment_optimizer_tpu.models.cluster import (  # noqa: E402
    demo_assignment,
    demo_broker_list,
    demo_topology,
)


@pytest.fixture
def demo():
    """The reference's worked demo (README.md:27-63): golden test #1."""
    return demo_assignment(), demo_broker_list(), demo_topology()


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def pytest_sessionfinish(session, exitstatus):
    """The sanitizer gate: an armed KAO_LSAN run that recorded any
    violation fails the session even when every test passed (the
    violation may have happened on a daemon thread no test asserts
    on). Deliberate-trip tests record into ``lsan.scope()`` ledgers,
    which never land here."""
    if _LSAN is None:
        return
    viol = _LSAN.violations()
    if viol and exitstatus == 0:
        lines = "\n".join(f"  {v.kind}: {v.detail}" for v in viol[:20])
        print(f"\nKAO_LSAN: {len(viol)} lock-sanitizer violation(s):"
              f"\n{lines}")  # kao: disable=KAO106 -- pytest gate output
        session.exitstatus = 1
