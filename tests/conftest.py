"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh so every
multi-chip code path (shard_map, psum over ICI) runs in CI without TPU
hardware — the analogue of the reference-style 'test multi-node without a
cluster' strategy (SURVEY.md §4.5)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from kafka_assignment_optimizer_tpu.models.cluster import (  # noqa: E402
    demo_assignment,
    demo_broker_list,
    demo_topology,
)


@pytest.fixture
def demo():
    """The reference's worked demo (README.md:27-63): golden test #1."""
    return demo_assignment(), demo_broker_list(), demo_topology()


@pytest.fixture
def rng():
    return np.random.default_rng(0)
