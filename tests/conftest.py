"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh so every
multi-chip code path (shard_map, psum over ICI) runs in CI without TPU
hardware — the analogue of the reference-style 'test multi-node without a
cluster' strategy (SURVEY.md §4.5)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# a site plugin may have force-registered an accelerator platform and
# overridden the env var programmatically; the config update re-selects
# CPU as long as no backend has been initialized yet — assert loudly
# rather than letting the suite quietly run on the wrong platform
jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu" and jax.device_count() == 8, (
    f"tests need the 8-device CPU mesh, got {jax.device_count()} "
    f"{jax.default_backend()} device(s); a plugin initialized JAX first"
)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from kafka_assignment_optimizer_tpu.models.cluster import (  # noqa: E402
    demo_assignment,
    demo_broker_list,
    demo_topology,
)


@pytest.fixture
def demo():
    """The reference's worked demo (README.md:27-63): golden test #1."""
    return demo_assignment(), demo_broker_list(), demo_topology()


@pytest.fixture
def rng():
    return np.random.default_rng(0)
