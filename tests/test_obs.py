"""Solve-trace telemetry tests (ISSUE 3): the span tracer itself, the
structured logger, and the CI guarantee that a CPU solve with tracing
enabled reports every pipeline phase exactly once with per-chunk
annealing stats (docs/OBSERVABILITY.md)."""

import io
import threading
from collections import Counter

from kafka_assignment_optimizer_tpu import optimize
from kafka_assignment_optimizer_tpu.obs import log as olog
from kafka_assignment_optimizer_tpu.obs import trace as otrace

PHASES = ("bounds", "constructor", "seed", "ladder", "polish", "verify")


def _names(span_dict, acc=None):
    acc = [] if acc is None else acc
    acc.append(span_dict["name"])
    for c in span_dict.get("spans", []):
        _names(c, acc)
    return acc


def _find(span_dict, name):
    if span_dict["name"] == name:
        return span_dict
    for c in span_dict.get("spans", []):
        hit = _find(c, name)
        if hit is not None:
            return hit
    return None


# --------------------------------------------------------------------------
# tracer unit surface
# --------------------------------------------------------------------------


def test_span_nesting_and_attrs():
    tr = otrace.begin(True, name="t")
    with otrace.span("a", x=1) as sp:
        assert sp.attrs["x"] == 1
        with otrace.span("b"):
            otrace.set_attrs(y=2)
        sp.set(z=3)
    rep = otrace.finish(tr)
    a = rep["spans"]["spans"][0]
    assert a["name"] == "a" and a["attrs"] == {"x": 1, "z": 3}
    b = a["spans"][0]
    assert b["name"] == "b" and b["attrs"] == {"y": 2}
    assert a["wall_s"] >= b["wall_s"] >= 0
    assert rep["phases"]["a"] == a["wall_s"]
    assert rep["trace_id"] == tr.trace_id


def test_disabled_path_is_shared_noop():
    """With no active trace every instrumentation call is a no-op; in
    particular span() returns one shared nullcontext — no allocation."""
    assert otrace.current_span() is None
    ctx1 = otrace.span("x", a=1)
    ctx2 = otrace.span("y")
    assert ctx1 is ctx2  # the shared disabled-path context manager
    with ctx1 as sp:
        assert sp is None
    otrace.mark("z", skipped=True)
    otrace.set_attrs(a=1)
    otrace.set_trajectory(rounds=1)
    assert otrace.current_trace_id() is None
    fn = otrace.wrap("w", lambda: 42)
    assert fn() == 42  # returned unchanged


def test_span_records_error_and_propagates():
    tr = otrace.begin(True)
    try:
        with otrace.span("boom"):
            raise RuntimeError("kaput")
    except RuntimeError:
        pass
    rep = otrace.finish(tr)
    sp = rep["spans"]["spans"][0]
    assert "kaput" in sp["attrs"]["error"]


def test_wrap_crosses_threads():
    tr = otrace.begin(True)
    seen: list = []
    fn = otrace.wrap("worker", lambda: otrace.current_trace_id(), k="v")
    t = threading.Thread(target=lambda: seen.append(fn()))
    t.start()
    t.join(timeout=10)
    rep = otrace.finish(tr)
    assert seen == [tr.trace_id]
    sp = rep["spans"]["spans"][0]
    assert sp["name"] == "worker" and sp["attrs"]["k"] == "v"
    assert sp["wall_s"] is not None


def test_nested_begin_restores_outer_trace():
    outer = otrace.begin(True)
    inner = otrace.begin(True)
    assert otrace.current_trace_id() == inner.trace_id
    otrace.finish(inner)
    assert otrace.current_trace_id() == outer.trace_id
    otrace.finish(outer)
    assert otrace.current_trace_id() is None


def test_report_ring_put_get_evict():
    ring = otrace.ReportRing(capacity=2)
    for i in range(3):
        ring.put({"trace_id": f"t{i}"})
    assert ring.get("t0") is None  # evicted, oldest first
    assert ring.get("t2")["trace_id"] == "t2"
    assert ring.ids() == ["t2", "t1"]  # newest first


def test_phase_histogram_observation():
    otrace.observe_phase("_test_phase", 0.05)
    otrace.observe_phase("_test_phase", 30.0)
    snap = otrace.phase_snapshot()["_test_phase"]
    assert snap["count"] == 2
    assert abs(snap["sum"] - 30.05) < 1e-6
    # cumulative buckets: 0.05 lands in le=0.1 and every wider bucket
    by_le = dict(snap["buckets"])
    assert by_le["0.1"] == 1 and by_le["60.0"] == 2


# --------------------------------------------------------------------------
# structured logger
# --------------------------------------------------------------------------


def test_structured_log_single_line_kv():
    buf = io.StringIO()
    olog.log("x", _stream=buf, n=3, msg="a b", skip=None, f=0.123456789)
    line = buf.getvalue()
    assert line.endswith("\n") and line.count("\n") == 1
    line = line.strip()
    assert "level=info" in line and "event=x" in line
    assert 'msg="a b"' in line and "n=3" in line
    assert "skip" not in line  # None fields dropped
    buf2 = io.StringIO()
    olog.warn("bad thing", _stream=buf2, why='he said "no"')
    w = buf2.getvalue().strip()
    assert "level=warn" in w and 'event="bad thing"' in w
    assert '\\"no\\"' in w


def test_log_includes_active_trace_id():
    tr = otrace.begin(True)
    buf = io.StringIO()
    olog.log("x", _stream=buf)
    otrace.finish(tr)
    assert f"trace_id={tr.trace_id}" in buf.getvalue()


# --------------------------------------------------------------------------
# CI end-to-end: the engine's span tree (tier-1 acceptance)
# --------------------------------------------------------------------------


def test_cpu_solve_trace_covers_every_phase_once(demo):
    """One CPU solve end-to-end with tracing enabled: the span tree
    must contain every pipeline phase exactly once, the ladder must
    carry per-chunk annealing stats, and the report must be registered
    under its trace ID (the acceptance criterion for ISSUE 3)."""
    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="tpu", engine="chain",
                   batch=8, rounds=4, steps_per_round=60, trace=True)
    stats = res.solve.stats
    rep = stats["solve_report"]
    assert rep["trace_id"] == stats["trace_id"]
    counts = Counter(_names(rep["spans"]))
    for ph in PHASES:
        assert counts[ph] == 1, (ph, counts)
    # the explicit engine knob disables the constructor race: its span
    # must still be present, marked skipped
    ctor = _find(rep["spans"], "constructor")
    assert ctor["attrs"]["skipped"] is True
    # the ladder ran: per-chunk annealing stats on every chunk span
    ladder = _find(rep["spans"], "ladder")
    chunks = [s for s in ladder.get("spans", []) if s["name"] == "chunk"]
    assert chunks, ladder
    for ch in chunks:
        at = ch["attrs"]
        # dispatch_s (host enqueue) vs device_s (blocked on results) vs
        # boundary_overlap_s (host boundary work hidden behind the next
        # in-flight chunk) — the pipelined-dispatch accounting
        # (docs/PIPELINE.md)
        for k in ("rounds", "t_hi", "t_lo", "energy_before",
                  "energy_after", "accepts", "declines", "dispatch_s",
                  "device_s", "boundary_overlap_s"):
            assert k in at, (k, at)
        assert at["t_hi"] >= at["t_lo"]
        assert at["accepts"] + at["declines"] == max(0, at["rounds"] - 1)
    # phases dict covers the whole pipeline with finite seconds
    for ph in PHASES:
        assert rep["phases"][ph] >= 0.0
    # trajectory summary present for a solve that actually annealed
    ann = rep["annealing"]
    assert ann["rounds"] == 4 and len(ann["energy_curve"]) == 4
    assert ann["improved_rounds"] + ann["plateau_rounds"] == 3
    # report retrievable from the process-wide ring buffer
    assert otrace.RECENT.get(stats["trace_id"])["trace_id"] == (
        stats["trace_id"]
    )
    # tracing never changed the answer
    assert res.report()["feasible"]


def test_constructed_solve_trace_still_covers_every_phase(demo):
    """The default demo solve usually wins a constructor race and skips
    the device entirely — the span tree must STILL show every phase
    exactly once (skipped phases are zero-duration marks)."""
    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="tpu", trace=True)
    rep = res.solve.stats["solve_report"]
    counts = Counter(_names(rep["spans"]))
    for ph in PHASES:
        assert counts[ph] == 1, (ph, counts)
    if res.solve.stats["engine"] == "construct":
        assert _find(rep["spans"], "ladder")["attrs"]["skipped"] is True
        assert _find(rep["spans"], "polish")["attrs"]["skipped"] is True


def test_constructor_subphase_spans_and_histograms(demo):
    """ISSUE 10 satellite: the constructor's host work is attributed to
    sub-phase spans — bounds_flow (the flow/LP bound computation),
    greedy / reseat (the racer's two loops), adopt (taking the
    constructed plan) — which roll up into the report's phases dict and
    the kao_phase_seconds histograms, so flight records and bench's
    construct_host_s column can tell the vectorized loops apart from
    overlap wait."""
    from kafka_assignment_optimizer_tpu.models.instance import (
        build_instance,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu.engine import (
        solve_tpu,
    )
    from kafka_assignment_optimizer_tpu.utils import gen

    # the default demo solve wins a constructor race: bounds_flow runs
    # in the bounds worker, adopt on the main thread
    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="tpu", trace=True)
    rep = res.solve.stats["solve_report"]
    assert _find(rep["spans"], "bounds_flow") is not None
    assert _find(rep["spans"], "adopt") is not None
    assert rep["phases"].get("bounds_flow", 0) >= 0
    assert rep["phases"].get("adopt", 0) >= 0

    # a slack-caps, symmetry-free instance above the exact-race size
    # takes the greedy+reseat racer: its two loops get their own spans
    sc = gen.adversarial(**gen.SMOKE_KWARGS["adversarial"])
    inst = build_instance(sc.current, sc.broker_list, sc.topology)
    # prewarm bounds so the racer certifies inside the race window
    # deterministically even on a loaded machine
    inst.move_lower_bound_exact()
    inst.weight_upper_bound()
    res2 = solve_tpu(inst, seed=0, trace=True)
    rep2 = res2.stats["solve_report"]
    assert _find(rep2["spans"], "greedy") is not None
    assert _find(rep2["spans"], "reseat") is not None
    # summed sub-phase seconds land in the phases dict (obs.trace
    # SUB_PHASES roll-up) without disturbing the root-phase vocabulary
    assert rep2["phases"]["greedy"] >= 0
    assert rep2["phases"]["reseat"] >= 0
    counts = Counter(_names(rep2["spans"]))
    for ph in PHASES:
        assert counts[ph] == 1, (ph, counts)
    # and feed the kao_phase_seconds{phase=} histograms
    snap = otrace.phase_snapshot()
    for sub in ("bounds_flow", "greedy", "reseat", "adopt"):
        assert sub in snap, (sub, sorted(snap))
        assert snap[sub]["count"] >= 1


def test_tracing_disabled_by_default(demo):
    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="tpu", engine="chain",
                   batch=8, rounds=2, steps_per_round=50)
    assert "solve_report" not in res.solve.stats
    assert "trace_id" not in res.solve.stats


def test_batch_solve_trace(demo):
    """solve_tpu_batch under a trace: one shared report, lane stats
    carry the trace ID, chunk spans under the ladder."""
    from kafka_assignment_optimizer_tpu.models.instance import (
        build_instance,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu.engine import (
        solve_tpu_batch,
    )

    current, brokers, topo = demo
    insts = [build_instance(current, brokers, topo) for _ in range(2)]
    results = solve_tpu_batch(insts, seeds=0, engine="sweep", rounds=8,
                              trace=True)
    assert len(results) == 2
    tids = {r.stats["trace_id"] for r in results}
    assert len(tids) == 1
    rep = results[0].stats["solve_report"]
    assert rep["trace_id"] in tids and rep["name"] == "solve_tpu_batch"
    counts = Counter(_names(rep["spans"]))
    for ph in PHASES:
        assert counts[ph] == 1, (ph, counts)
    ladder = _find(rep["spans"], "ladder")
    assert any(s["name"] == "chunk" for s in ladder.get("spans", []))
    assert otrace.RECENT.get(rep["trace_id"]) is not None
