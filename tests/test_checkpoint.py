"""Checkpoint/resume + profiling hooks (SURVEY.md §5 aux subsystems)."""

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance, optimize
from kafka_assignment_optimizer_tpu.utils import checkpoint as ckpt

from tests.test_tpu_engine import random_cluster


def test_checkpoint_roundtrip(demo, tmp_path):
    current, brokers, topo = demo
    inst = build_instance(current, brokers, topo)
    path = tmp_path / "plan.npz"
    a = np.asarray(inst.a0).copy()
    a[a >= inst.num_brokers] = 0
    ckpt.save(path, inst, a, meta={"note": "test"})
    back = ckpt.load(path, inst)
    np.testing.assert_array_equal(back, a)


def test_checkpoint_rejects_other_instance(demo, tmp_path, rng):
    current, brokers, topo = demo
    inst = build_instance(current, brokers, topo)
    path = tmp_path / "plan.npz"
    ckpt.save(path, inst, np.zeros((inst.num_parts, inst.max_rf), np.int32))
    other_cur, other_brokers, other_topo = random_cluster(rng, 8, 10, 2, 2)
    other = build_instance(other_cur, other_brokers, other_topo)
    assert ckpt.load(path, other) is None
    assert ckpt.load(tmp_path / "missing.npz", inst) is None


def test_solve_saves_and_resumes(demo, tmp_path):
    current, brokers, topo = demo
    path = str(tmp_path / "demo.npz")
    r1 = optimize(current, brokers, topo, solver="tpu",
                  batch=8, rounds=4, steps_per_round=100, checkpoint=path)
    assert (tmp_path / "demo.npz").exists()
    assert not r1.solve.stats["resumed_from_checkpoint"]
    # second solve resumes from the saved optimum and must stay there
    r2 = optimize(current, brokers, topo, solver="tpu",
                  batch=8, rounds=2, steps_per_round=50, checkpoint=path)
    assert r2.solve.stats["resumed_from_checkpoint"]
    assert r2.replica_moves == 1
    assert r2.solve.objective >= r1.solve.objective


def test_profile_trace_written(demo, tmp_path):
    current, brokers, topo = demo
    prof = tmp_path / "trace"
    optimize(current, brokers, topo, solver="tpu",
             batch=8, rounds=2, steps_per_round=50,
             profile_dir=str(prof))
    # jax.profiler.trace writes a plugins/ dir with one trace per run
    produced = list(prof.rglob("*"))
    assert produced, "profiler trace directory is empty"
