"""kao-check — the static-analysis suite's own test coverage.

Three layers (docs/ANALYSIS.md):

- per-rule fixtures: one positive (must flag) and one negative (must
  stay silent) snippet per AST rule, run through ``lint_source``;
- jaxpr contracts: the checker passes on the REAL sweep/lane/chain
  solvers and detects seeded violations (float64, host callbacks);
- self-check: ``python -m kafka_assignment_optimizer_tpu.analysis``
  exits 0 on the repo's own package tree and non-zero on a fixture
  violation — the property CI enforces.

Plus the runtime sanitizer's counters/guards and their /metrics
exposition.
"""

from __future__ import annotations

import subprocess
import sys
import textwrap

import pytest

from kafka_assignment_optimizer_tpu.analysis import lint_paths
from kafka_assignment_optimizer_tpu.analysis.rules_ast import lint_source


def _lint(snippet: str, rel: str = "solvers/tpu/fixture.py"):
    return lint_source(textwrap.dedent(snippet), "fixture.py", rel=rel)


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------- KAO101

POS_101 = """
    import jax

    def run(m, state, temps):
        f = jax.jit(step, donate_argnums=(1,))
        out = f(m, state, temps)
        return state[0]  # donated: dead buffer
"""

NEG_101 = """
    import jax

    def run(m, state, temps):
        f = jax.jit(step, donate_argnums=(1,))
        state, best = f(m, state, temps)  # rebinds to the RETURNED state
        return state[0]
"""


def test_kao101_donated_reuse():
    assert "KAO101" in _rules(_lint(POS_101))
    assert "KAO101" not in _rules(_lint(NEG_101))


# ---------------------------------------------------------------- KAO102

POS_102 = """
    import numpy as np

    def init(seed, n):
        tile = np.broadcast_to(seed, (n, 4, 4))
        return (tile, np.zeros(n), tile)  # two leaves, ONE base buffer
"""

NEG_102 = """
    import numpy as np

    def init(seed, n):
        tile = np.broadcast_to(seed, (n, 4, 4))
        return (np.array(tile), np.zeros(n), np.array(tile))
"""

NEG_102_JNP = """
    import jax.numpy as jnp

    def traced(a, n):
        x = jnp.broadcast_to(a, (n, 4))
        return x + x  # functional device op: no host buffer aliasing
"""


def test_kao102_shared_broadcast_base():
    assert "KAO102" in _rules(_lint(POS_102))
    assert "KAO102" not in _rules(_lint(NEG_102))
    assert "KAO102" not in _rules(_lint(NEG_102_JNP))


# ---------------------------------------------------------------- KAO103

POS_103 = """
    import numpy as np

    def ladder(n):
        return np.array([2.0, 1.0, 0.5])  # float64 on host
"""

POS_103_DTYPE = """
    import numpy as np

    def ladder(n):
        return np.zeros(n, dtype=float)
"""

NEG_103 = """
    import numpy as np

    def ladder(n):
        return np.array([2.0, 1.0, 0.5], dtype=np.float32)
"""


def test_kao103_float64_in_device_path():
    assert "KAO103" in _rules(_lint(POS_103))
    assert "KAO103" in _rules(_lint(POS_103_DTYPE))
    assert "KAO103" not in _rules(_lint(NEG_103))
    # host-side oracle paths are out of scope: float64 LP math is fine
    assert "KAO103" not in _rules(
        _lint(POS_103, rel="models/bounds.py")
    )


# ---------------------------------------------------------------- KAO104

POS_104 = """
    import jax

    def sample(n):
        key = jax.random.PRNGKey(0)
        a = jax.random.randint(key, (n,), 0, 4)
        b = jax.random.randint(key, (n,), 0, 4)  # identical stream!
        return a, b
"""

NEG_104 = """
    import jax

    def sample(n):
        key = jax.random.PRNGKey(0)
        ka, kb = jax.random.split(key)
        a = jax.random.randint(ka, (n,), 0, 4)
        b = jax.random.randint(kb, (n,), 0, 4)
        return a, b
"""


def test_kao104_key_reuse():
    assert "KAO104" in _rules(_lint(POS_104))
    assert "KAO104" not in _rules(_lint(NEG_104))


# ---------------------------------------------------------------- KAO105

POS_105 = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(a, t):
        if jnp.any(a > t):  # traced value in a Python branch
            return a - 1
        return a
"""

POS_105_FACTORY = """
    def make_solver_fn(n):
        def solve(m, a, temps):
            while a > 0:  # traced param in a Python loop
                a = a - 1
            return a
        return solve
"""

NEG_105 = """
    import jax

    @jax.jit
    def step(a, t, axis_name=None):
        if axis_name is None:  # static structure test
            return a
        if a.shape[0] > 2:  # shapes are static at trace time
            return a + t
        return a
"""


def test_kao105_traced_branch():
    assert "KAO105" in _rules(_lint(POS_105))
    assert "KAO105" in _rules(_lint(POS_105_FACTORY))
    assert "KAO105" not in _rules(_lint(NEG_105))


# ---------------------------------------------------------------- KAO106

POS_106 = """
    def handle(req):
        print("served", req)
"""

NEG_106_LOG = """
    from .obs import log as _olog

    def handle(req):
        _olog.info("served", req=req)
"""


def test_kao106_bare_print():
    assert "KAO106" in _rules(_lint(POS_106))
    assert "KAO106" not in _rules(_lint(NEG_106_LOG))
    # the structured logger's own emit site is the one allowed print
    assert "KAO106" not in _rules(
        _lint("def emit(line):\n    print(line)\n", rel="obs/log.py")
    )


# ---------------------------------------------------------------- KAO107

POS_107 = """
    def render(n):
        lines = []
        lines.append(f"kao_new_counter_total {n}")
        return lines
"""

NEG_107 = """
    def render(n):
        lines = []
        lines.append("# HELP kao_new_counter_total new counter")
        lines.append("# TYPE kao_new_counter_total counter")
        lines.append(f"kao_new_counter_total {n}")
        return lines
"""

NEG_107_PROSE = """
    NAME = "kao_current_span"  # a contextvar name, not a metric sample
"""


def test_kao107_metrics_help_type():
    assert "KAO107" in _rules(_lint(POS_107))
    assert "KAO107" not in _rules(_lint(NEG_107))
    assert "KAO107" not in _rules(_lint(NEG_107_PROSE))


# ---------------------------------------------------------------- KAO109

POS_109 = """
    def weight_upper_bound(inst):
        total = 0
        for p in range(inst.num_parts):
            total += int(inst.rf[p])
        return total
"""

POS_109_SPLIT = """
    def certify(inst):
        P = inst.num_parts
        acc = []
        for p in range(P):
            acc.append(p)
        return acc
"""

NEG_109_VECTORIZED = """
    import numpy as np

    def weight_upper_bound(inst):
        return int(inst.rf[: inst.num_parts].sum())
"""


def test_kao109_partition_loop_in_hot_modules():
    # the rule is path-scoped to the bound/reseat hot modules
    assert "KAO109" in _rules(_lint(POS_109, rel="models/bounds.py"))
    assert "KAO109" in _rules(_lint(POS_109, rel="models/reseat.py"))
    assert "KAO109" in _rules(
        _lint(POS_109_SPLIT, rel="models/bounds.py")
    )
    assert "KAO109" not in _rules(
        _lint(NEG_109_VECTORIZED, rel="models/bounds.py")
    )
    # other modules may loop (the engine's chunk loop, tests, CLI)
    assert "KAO109" not in _rules(
        _lint(POS_109, rel="solvers/tpu/engine.py")
    )
    # suppressible with justification, like every rule
    sup = POS_109.replace(
        "for p in range(inst.num_parts):",
        "for p in range(inst.num_parts):  "
        "# kao: disable=KAO109 -- cold fallback, never on the hot path",
    )
    assert _rules(_lint(sup, rel="models/bounds.py")) == []


# ---------------------------------------------------------------- KAO110

POS_110_CAPTURE = """
    def make_lane_stepper_fn(n_chains, lam):
        def step(m, a, temp):
            return a * lam  # config captured: specializes per config
        return step
"""

POS_110_LOCAL = """
    def make_lane_solver(cfg):
        temp_scale = cfg.temp_scale
        def solve(m, a, temps):
            return a, temps * temp_scale
        return solve
"""

POS_110_COERCE = """
    def make_portfolio_stepper(m):
        lam = float(m.lam)  # trace-time constant per config
        def step(a):
            return a
        return step
"""

NEG_110_MODEL_DATA = """
    def make_lane_stepper_fn(n_chains):
        def step(m, a, temp):
            # config as data: read off the model pytree inside the
            # traced body — one executable serves every config
            return a * m.lam + temp * m.temp_scale
        return step
"""

NEG_110_SHADOWED = """
    def make_thing(n):
        def inner(lam):
            return lam + n  # inner's OWN parameter, not a capture
        return inner
"""

NEG_110_NOT_FACTORY = """
    def summarize(m):
        return float(m.lam)  # host provenance read, not a factory
"""


def test_kao110_lane_config_capture_in_factories():
    assert "KAO110" in _rules(_lint(POS_110_CAPTURE))
    assert "KAO110" in _rules(_lint(POS_110_LOCAL))
    assert "KAO110" in _rules(_lint(POS_110_COERCE))
    assert "KAO110" not in _rules(_lint(NEG_110_MODEL_DATA))
    assert "KAO110" not in _rules(_lint(NEG_110_SHADOWED))
    assert "KAO110" not in _rules(_lint(NEG_110_NOT_FACTORY))
    # suppressible with justification, like every rule
    sup = POS_110_CAPTURE.replace(
        "return a * lam  # config captured: specializes per config",
        "return a * lam  "
        "# kao: disable=KAO110 -- fixture: deliberate specialization",
    )
    assert _rules(_lint(sup)) == []


# ---------------------------------------------------------------- KAO111

POS_111_REQUEST = """
    import http.client

    def proxy(url, body):
        conn = http.client.HTTPConnection(url)
        conn.request("POST", "/submit", body=body)
        return conn.getresponse().read()
"""

POS_111_URLOPEN = """
    import urllib.request

    def fanout(url):
        with urllib.request.urlopen(url + "/clusters") as r:
            return r.read()
"""

NEG_111_INJECTED = """
    import http.client
    from .obs import trace as _otrace

    def proxy(url, body):
        hdrs = {"traceparent": _otrace.inject()}
        conn = http.client.HTTPConnection(url)
        conn.request("POST", "/submit", body=body, headers=hdrs)
        return conn.getresponse().read()
"""

NEG_111_HEADER_PARAM = """
    import http.client

    def proxy_once(url, body, headers=None):
        # propagation is the CALLER's contract: headers thread through
        conn = http.client.HTTPConnection(url)
        conn.request("POST", "/submit", body=body,
                     headers=headers or {})
        return conn.getresponse().read()
"""


def test_kao111_uninjected_http_in_serving_tier():
    # the rule is scoped to the serving tier (serve.py, fleet/)
    assert "KAO111" in _rules(_lint(POS_111_REQUEST,
                                    rel="fleet/router.py"))
    assert "KAO111" in _rules(_lint(POS_111_URLOPEN, rel="serve.py"))
    assert "KAO111" not in _rules(_lint(NEG_111_INJECTED,
                                        rel="fleet/router.py"))
    assert "KAO111" not in _rules(_lint(NEG_111_HEADER_PARAM,
                                        rel="fleet/router.py"))
    # out of scope: an engine module making an HTTP call is not this
    # rule's business
    assert "KAO111" not in _rules(_lint(POS_111_REQUEST,
                                        rel="solvers/tpu/engine.py"))
    # suppressible with justification (the health-poll dogfood shape)
    sup = POS_111_URLOPEN.replace(
        'with urllib.request.urlopen(url + "/clusters") as r:',
        "# kao: disable=KAO111 -- read-only poll, no active request\n"
        '        with urllib.request.urlopen(url + "/clusters") as r:',
    )
    assert _rules(_lint(sup, rel="serve.py")) == []


# ---------------------------------------------------------------- KAO112

POS_112 = """
    import numpy as np

    def stitch(inst, plans):
        out = np.full((inst.num_parts, 3), -1)
        for p in range(inst.num_parts):
            out[p] = plans[p]
        return out
"""

NEG_112_GROUP_LOOP = """
    import numpy as np

    def split(inst, n_groups):
        subs = []
        for g in range(n_groups):  # groups, not partitions: fine
            subs.append(g)
        return subs
"""


def test_kao112_partition_loop_in_decompose_modules():
    # the rule is path-scoped to the decompose hot modules
    assert "KAO112" in _rules(_lint(POS_112, rel="decompose/split.py"))
    assert "KAO112" in _rules(_lint(POS_112, rel="decompose/stitch.py"))
    # the KAO109 name-bound variant triggers here too (shared detector)
    assert "KAO112" in _rules(
        _lint(POS_109_SPLIT, rel="decompose/split.py")
    )
    # loops over groups/racks are the sanctioned shape
    assert "KAO112" not in _rules(
        _lint(NEG_112_GROUP_LOOP, rel="decompose/split.py")
    )
    # out of scope: the orchestrator may loop (it ranges over lanes),
    # and the bound/reseat modules stay KAO109's business, not 112's
    assert "KAO112" not in _rules(
        _lint(POS_112, rel="decompose/__init__.py")
    )
    assert "KAO112" not in _rules(_lint(POS_112, rel="models/bounds.py"))
    # suppressible with justification, like every rule
    sup = POS_112.replace(
        "for p in range(inst.num_parts):",
        "for p in range(inst.num_parts):  "
        "# kao: disable=KAO112 -- cold fallback, never on the hot path",
    )
    assert _rules(_lint(sup, rel="decompose/split.py")) == []


# ---------------------------------------------------------------- KAO113

POS_113_ITEM = """
    from jax import lax

    def sweep(state, temps):
        def body(carry, temp):
            carry, best = step(carry, temp)
            done = best.item() > 0  # host sync inside the fused scan
            return carry, done
        return lax.scan(body, state, temps)
"""

POS_113_ASARRAY = """
    import numpy as np
    from jax import lax

    def sweep(state, temps):
        def body(carry, temp):
            carry = step(carry, temp)
            snap = np.asarray(carry[0])  # concretizes a tracer
            return carry, snap
        return lax.scan(body, state, temps)
"""

POS_113_BRANCH = """
    from jax import lax

    def sweep(state, temps):
        def body(carry, temp):
            if carry:  # Python branch on the traced carry
                carry = step(carry, temp)
            return carry, None
        return lax.scan(body, state, temps)
"""

NEG_113_DEVICE_RESIDENT = """
    import numpy as np
    import jax.numpy as jnp
    from jax import lax

    def sweep(state, temps):
        def body(carry, temp):
            new, hit = step(carry, temp)
            # masked no-op early exit: the decision stays on-device
            carry = jnp.where(hit, carry, new)
            ok = jnp.asarray(hit)  # jnp stays legal inside the body
            return carry, ok
        out, execd = lax.scan(body, state, temps)
        return np.asarray(execd)  # host fetch AFTER the scan: fine
"""


def test_kao113_host_sync_in_scan_body():
    assert "KAO113" in _rules(_lint(POS_113_ITEM))
    assert "KAO113" in _rules(_lint(POS_113_ASARRAY))
    assert "KAO113" in _rules(_lint(POS_113_BRANCH))
    # the sanctioned megachunk shape: where-selects on the carry,
    # jnp inside the body, host fetches only after the scan retires
    assert "KAO113" not in _rules(_lint(NEG_113_DEVICE_RESIDENT))
    # suppressible with justification, like every rule
    sup = POS_113_ITEM.replace(
        "done = best.item() > 0  # host sync inside the fused scan",
        "done = best.item() > 0  "
        "# kao: disable=KAO113 -- interpret-mode debug helper",
    )
    assert "KAO113" not in _rules(_lint(sup))


# ---------------------------------------------------------------- KAO114

POS_114 = """
    import time

    def run_chunk(dispatch, state, log):
        t0 = time.perf_counter()
        out = dispatch(state)
        dt = time.perf_counter() - t0
        log.info("chunk", seconds=dt)  # the ledger never sees this
        return out
"""

NEG_114_FUNNEL = """
    import time

    def run_chunk(dispatch, state, _flight):
        t0 = time.perf_counter()
        out = dispatch(state)
        dt = time.perf_counter() - t0
        _flight.note_window("dispatch", dt)
        return out
"""

NEG_114_RESULT_FIELD = """
    import time

    def run_chunk(dispatch, state, r):
        t0 = time.perf_counter()
        out = dispatch(state)
        r.device_s += time.perf_counter() - t0  # lands on the record
        return out, time.perf_counter() - t0  # returned: caller funnels
"""

NEG_114_CHAIN = """
    import time

    def run_chunk(dispatch, state, overlap_ok, sp):
        t0 = time.perf_counter()
        out = dispatch(state)
        dt = time.perf_counter() - t0
        overlap = dt if overlap_ok else 0.0  # taint follows the chain
        chunk_attrs(sp, overlap)
        return out
"""

NEG_114_HEADROOM = """
    import time

    def run_chunk(dispatch, state, deadline):
        if deadline - time.perf_counter() < 0.1:  # remaining budget,
            return None                           # not elapsed wall
        return dispatch(state)
"""

NEG_114_NO_DISPATCH_SITE = """
    import time

    def tick(log):
        t0 = time.perf_counter()
        work()
        dt = time.perf_counter() - t0
        log.info("tick", seconds=dt)
"""


def test_kao114_time_delta_outside_funnel():
    # the rule is path-scoped to the dispatch hot modules
    assert "KAO114" in _rules(
        _lint(POS_114, rel="solvers/tpu/engine.py")
    )
    assert "KAO114" in _rules(_lint(POS_114, rel="parallel/mesh.py"))
    # out of scope: the same shape elsewhere is whatever-module's
    # business, not the accounting funnel's
    assert "KAO114" not in _rules(_lint(POS_114))
    assert "KAO114" not in _rules(_lint(POS_114, rel="obs/flight.py"))
    # deltas that reach the funnel (directly, via a result field or
    # return, or through an assignment chain) are the sanctioned shape
    assert "KAO114" not in _rules(
        _lint(NEG_114_FUNNEL, rel="solvers/tpu/engine.py")
    )
    assert "KAO114" not in _rules(
        _lint(NEG_114_RESULT_FIELD, rel="solvers/tpu/engine.py")
    )
    assert "KAO114" not in _rules(
        _lint(NEG_114_CHAIN, rel="solvers/tpu/engine.py")
    )
    # deadline-headroom checks (timer on the RIGHT) are control flow
    assert "KAO114" not in _rules(
        _lint(NEG_114_HEADROOM, rel="solvers/tpu/engine.py")
    )
    # functions that never reach a dispatch/compile site are host
    # helpers timing themselves — out of the ledger's jurisdiction
    assert "KAO114" not in _rules(
        _lint(NEG_114_NO_DISPATCH_SITE, rel="solvers/tpu/engine.py")
    )
    # suppressible with justification, like every rule
    sup = POS_114.replace(
        "dt = time.perf_counter() - t0",
        "dt = time.perf_counter() - t0  "
        "# kao: disable=KAO114 -- test-only instrumentation",
    )
    assert "KAO114" not in _rules(
        _lint(sup, rel="solvers/tpu/engine.py")
    )


# ---------------------------------------------------------------- KAO115

POS_115_SHARDMAP = """
    def host(fn, mesh):
        return _shard_map(fn, mesh=mesh)  # placements left implicit
"""

POS_115_PJIT = """
    from jax.experimental.pjit import pjit

    def host(fn):
        return pjit(fn, donate_argnums=(1,))
"""

POS_115_MODULE_SNAPSHOT = """
    import jax

    DEVS = jax.devices()  # frozen at import
"""

POS_115_DEFAULT_ARG = """
    import jax

    def make_solver(devs=jax.devices()):
        return len(devs)
"""

POS_115_FACTORY_CAPTURE = """
    import jax

    def make_dispatch():
        devs = jax.devices()

        def dispatch(state):
            return shard(state, devs)  # closure pins the snapshot

        return dispatch
"""

NEG_115_EXPLICIT = """
    def host(fn, mesh, in_specs, out_specs):
        sharded = _shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )
        jitted = pjit(fn, in_shardings=in_specs, out_shardings=out_specs)
        return sharded, jitted
"""

NEG_115_LOCAL_USE = """
    import jax

    def make_mesh(n_devices=None):
        devs = jax.devices()  # resolved per call, used in this body
        return Mesh(devs[:n_devices], ("chains",))
"""

NEG_115_SHADOWED = """
    import jax

    def make_dispatch():
        devs = jax.devices()
        mesh = Mesh(devs, ("chains",))

        def dispatch(state, devs):
            return shard(state, devs)  # parameter, not the snapshot

        return dispatch, mesh
"""


def test_kao115_implicit_placement_sites():
    # implicit-placement dispatch sites, scoped to parallel/
    assert "KAO115" in _rules(
        _lint(POS_115_SHARDMAP, rel="parallel/mesh.py")
    )
    assert "KAO115" in _rules(_lint(POS_115_PJIT, rel="parallel/mesh.py"))
    # out of scope: other modules own their own dispatch idioms
    assert "KAO115" not in _rules(_lint(POS_115_SHARDMAP))
    # explicit specs on every site is the sanctioned shape
    assert "KAO115" not in _rules(
        _lint(NEG_115_EXPLICIT, rel="parallel/mesh.py")
    )


def test_kao115_stale_device_snapshots():
    # stale device snapshots: module scope, default arg, factory closure
    assert "KAO115" in _rules(
        _lint(POS_115_MODULE_SNAPSHOT, rel="parallel/mesh.py")
    )
    assert "KAO115" in _rules(
        _lint(POS_115_DEFAULT_ARG, rel="parallel/mesh.py")
    )
    assert "KAO115" in _rules(
        _lint(POS_115_FACTORY_CAPTURE, rel="parallel/mesh.py")
    )
    # a device list resolved and consumed inside one body is fine (the
    # make_mesh shape), as is a nested def shadowing the name
    assert "KAO115" not in _rules(
        _lint(NEG_115_LOCAL_USE, rel="parallel/mesh.py")
    )
    assert "KAO115" not in _rules(
        _lint(NEG_115_SHADOWED, rel="parallel/mesh.py")
    )


def test_kao115_suppressible_with_justification():
    # suppressible with justification, like every rule
    sup = POS_115_SHARDMAP.replace(
        "return _shard_map(fn, mesh=mesh)  # placements left implicit",
        "return _shard_map(fn, mesh=mesh)  "
        "# kao: disable=KAO115 -- fixture: replicated-only helper",
    )
    assert "KAO115" not in _rules(_lint(sup, rel="parallel/mesh.py"))


# ------------------------------------------------------------ suppression

def test_suppression_requires_justification():
    sup = 'def f(x):\n    print(x)  # kao: disable=KAO106 -- CLI UX\n'
    assert _rules(_lint(sup)) == []
    naked = 'def f(x):\n    print(x)  # kao: disable=KAO106\n'
    rules = _rules(_lint(naked))
    # a naked disable does not suppress AND is itself flagged
    assert "KAO106" in rules and "KAO100" in rules


def test_suppression_scope_is_one_line():
    # a standalone comment covers the line BELOW it...
    above = (
        "def f(x):\n"
        "    # kao: disable=KAO106 -- UX\n"
        "    print(x)\n"
    )
    assert _rules(_lint(above)) == []
    # ...but a trailing comment covers only its own line: a copy-pasted
    # second violation underneath must still be reported
    leak = (
        "def f(x):\n"
        "    print(x)  # kao: disable=KAO106 -- UX\n"
        "    print(x)\n"
    )
    assert _rules(_lint(leak)) == ["KAO106"]


# ----------------------------------------------------------- jaxpr layer

def test_jaxpr_contracts_pass_on_real_solvers():
    from kafka_assignment_optimizer_tpu.analysis.contracts import (
        run_contracts,
    )

    rep = run_contracts()
    assert rep.ok, [f.render() for f in rep.findings]
    assert rep.checks_run >= 8


def test_jaxpr_walker_detects_violations():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kafka_assignment_optimizer_tpu.analysis.contracts import (
        _check_jaxpr,
    )

    def f64(x):
        return x + jnp.asarray(np.array([0.5, 1.5]))

    with jax.experimental.enable_x64():
        closed = jax.make_jaxpr(f64)(jnp.zeros(2, jnp.float64))
    found: list = []
    _check_jaxpr(closed, "f64", found)
    assert [f.rule for f in found] == ["KAO201"]

    def cb(x):
        jax.debug.callback(lambda v: None, x)
        return x * 2

    closed = jax.make_jaxpr(cb)(jnp.zeros(2, jnp.float32))
    found = []
    _check_jaxpr(closed, "cb", found)
    assert [f.rule for f in found] == ["KAO201"]


# ------------------------------------------------------------ self-check

def test_kao_check_exits_zero_on_repo():
    """The acceptance gate: the repo's own tree is clean under its own
    analyzer. Lint-only here (cheap, no second jax startup inside the
    gate); the jaxpr contract pass runs in-process in
    ``test_jaxpr_contracts_pass_on_real_solvers`` and end-to-end in the
    soak-tier full run below."""
    r = subprocess.run(
        [sys.executable, "-m", "kafka_assignment_optimizer_tpu.analysis",
         "--no-contracts"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 finding(s)" in r.stdout


@pytest.mark.slow
def test_kao_check_full_run_exits_zero_on_repo():
    """The exact CI invocation — lint + jaxpr contracts in a fresh
    interpreter. Marked slow: .github/workflows/kao-check.yml runs this
    command on every push, so no pytest gate needs to pay the second
    jax startup."""
    r = subprocess.run(
        [sys.executable, "-m", "kafka_assignment_optimizer_tpu.analysis"],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 finding(s)" in r.stdout


def test_kao_check_exits_nonzero_on_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(x):\n    print(x)\n")
    r = subprocess.run(
        [sys.executable, "-m", "kafka_assignment_optimizer_tpu.analysis",
         str(bad)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "KAO106" in r.stdout


def test_lint_paths_api_clean_on_package():
    assert lint_paths() == []


# -------------------------------------------------------------- sanitizer

@pytest.fixture
def sanitizer():
    from kafka_assignment_optimizer_tpu.analysis import sanitize

    sanitize.reset()
    sanitize.enable()
    yield sanitize
    sanitize.disable()
    sanitize.reset()


def test_sanitizer_recompile_budget(sanitizer):
    key = ("solver", "sig")
    for _ in range(sanitizer.compile_budget()):
        sanitizer.note_compile(key)  # within budget: silent
    with pytest.raises(sanitizer.RecompileBudgetError):
        sanitizer.note_compile(key)
    assert sanitizer.snapshot()["recompiles_total"] == 1


def test_sanitizer_trip_resets_episode(sanitizer):
    """A budget trip must not poison the key forever: the executable
    was never cached, so the next request's cold rebuild restarts the
    count instead of tripping on every later solve."""
    key = ("solver", "sig")
    for _ in range(sanitizer.compile_budget()):
        sanitizer.note_compile(key)
    with pytest.raises(sanitizer.RecompileBudgetError):
        sanitizer.note_compile(key)
    for _ in range(sanitizer.compile_budget()):
        sanitizer.note_compile(key)  # fresh episode: full budget again
    assert sanitizer.snapshot()["recompiles_total"] == 1


def test_nan_abort_counted_once_per_exception(sanitizer):
    e = FloatingPointError("nan")
    sanitizer.note_nan_abort_once(e, "inner")
    sanitizer.note_nan_abort_once(e, "outer")  # same exception object
    assert sanitizer.snapshot()["nan_aborts_total"] == 1


def test_kao_check_flag_guards(tmp_path):
    for argv in (["--contracts-only", "--no-contracts"],
                 ["--rule", "KAO999"]):
        r = subprocess.run(
            [sys.executable, "-m",
             "kafka_assignment_optimizer_tpu.analysis", *argv],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 2, (argv, r.stdout, r.stderr)


def test_sanitizer_forgets_evicted_keys(sanitizer):
    """LRU eviction resets the recompile sentinel: a key's post-evict
    rebuild is a legitimate cold compile, not thrash."""
    key = ("solver", "sig")
    for _ in range(sanitizer.compile_budget()):
        sanitizer.note_compile(key)
    sanitizer.forget_key(key)  # what mesh does on eviction
    for _ in range(sanitizer.compile_budget()):
        sanitizer.note_compile(key)  # full budget again, no trip
    assert sanitizer.snapshot()["recompiles_total"] == 0


def test_contracts_only_rejects_paths(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "kafka_assignment_optimizer_tpu.analysis",
         "--contracts-only", str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 2, r.stdout + r.stderr
    assert "does not take paths" in r.stderr


def test_sanitizer_nan_and_donation_counters(sanitizer):
    import numpy as np

    with pytest.raises(sanitizer.SanitizerError):
        sanitizer.check_host(np.array([1.0, np.nan], np.float32), "t")
    with pytest.raises(sanitizer.DonationReuseError):
        sanitizer.note_donation_reuse(("k",))
    snap = sanitizer.snapshot()
    assert snap["nan_aborts_total"] == 1
    assert snap["donation_reuse_total"] == 1
    assert snap["enabled"] == 1


def test_sanitizer_disabled_is_inert():
    from kafka_assignment_optimizer_tpu.analysis import sanitize

    sanitize.reset()
    assert not sanitize.enabled()
    import numpy as np

    sanitize.check_host(np.array([np.nan]), "t")  # no-op when off
    sanitize.note_compile(("k",))  # never raises when off
    assert sanitize.snapshot()["nan_aborts_total"] == 0


def test_sanitized_solve_smoke(sanitizer, demo):
    """KAO_SANITIZE acceptance: a small sweep solve under the armed
    sanitizer completes with ZERO sentinel trips."""
    from kafka_assignment_optimizer_tpu import optimize

    current, brokers, topo = demo
    res = optimize(current, brokers, topo, solver="tpu",
                   engine="sweep", batch=4, sweeps=16)
    assert res.report()["feasible"]
    snap = sanitizer.snapshot()
    assert snap["recompiles_total"] == 0
    assert snap["nan_aborts_total"] == 0
    assert snap["donation_reuse_total"] == 0


def test_sanitizer_counters_on_metrics(sanitizer):
    from kafka_assignment_optimizer_tpu.serve import render_metrics

    text = render_metrics()
    for fam in ("kao_sanitizer_recompiles_total",
                "kao_sanitizer_nan_aborts_total"):
        assert f"# HELP {fam} " in text
        assert f"# TYPE {fam} counter" in text
        assert any(
            line.startswith(fam + " ")
            for line in text.splitlines()
        ), text
