"""Batched multi-instance solve lanes (the PR-2 tentpole).

Pins the contract the coalescing dispatcher and the bench throughput
scenario rely on: a batched lane solve is ``jax.vmap`` of the
single-instance solver, so lane trajectories are BIT-IDENTICAL to
solving each instance alone with the same key — batching changes
throughput, never results. Covers both engines (sweep stateful, chain
stateless), the Pallas interpret-mode scorer under the lane vmap, the
engine-level ``solve_tpu_batch`` quality contract, and the unstackable
fallback."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import build_instance
from kafka_assignment_optimizer_tpu.parallel import mesh as pm
from kafka_assignment_optimizer_tpu.solvers.tpu import arrays
from kafka_assignment_optimizer_tpu.solvers.tpu.engine import solve_tpu_batch
from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed
from kafka_assignment_optimizer_tpu.utils import gen


def _adv_instance(seed: int, **overrides):
    kw = dict(n_brokers=32, n_topics_low=3, n_topics_high=3,
              parts_per_topic=10, seed=seed)
    kw.update(overrides)
    sc = gen.adversarial(**kw)
    return build_instance(sc.current, sc.broker_list, sc.topology)


def test_stack_models_requires_common_shape():
    a = arrays.from_instance(_adv_instance(7))
    b = arrays.from_instance(_adv_instance(8), num_parts=256)
    with pytest.raises(ValueError, match="common bucket"):
        arrays.stack_models([a, b])
    stacked = arrays.stack_models([a, a])
    assert stacked.a0.shape == (2, *a.a0.shape)


def test_sweep_lane_b1_bit_parity():
    """A B=1 lane solve through the vmapped stepper is bit-identical to
    the unbatched sweep solve from the same state and key."""
    inst = _adv_instance(7)
    m = arrays.from_instance(inst)
    seed = np.asarray(greedy_seed(inst), np.int32)
    mesh = pm.make_mesh()
    key = jax.random.PRNGKey(0)
    temps = arrays.geometric_temps(2.0, 0.02, 16)

    state = pm.init_sweep_state(m, jnp.asarray(seed), key, mesh, 2)
    _st, ba1, bk1, cv1 = pm.solve_on_mesh(
        m, None, None, mesh, 2, 16, 1, engine="sweep", temps=temps,
        state=state,
    )
    out = pm.solve_lanes(
        arrays.stack_models([m]), mesh, 2, temps,
        lane_seeds=seed[None], keys=jnp.stack([key]), engine="sweep",
    )
    _st2, ba2, bk2, cv2 = out
    assert np.array_equal(np.asarray(ba1), np.asarray(ba2)[:, 0])
    assert np.array_equal(np.asarray(bk1), np.asarray(bk2)[:, 0])
    assert np.array_equal(np.asarray(cv1), np.asarray(cv2)[:, 0])


def test_chain_lane_b1_bit_parity():
    """Same parity contract for the chain engine's stateless lane path."""
    inst = _adv_instance(7)
    m = arrays.from_instance(inst)
    seed = np.asarray(greedy_seed(inst), np.int32)
    mesh = pm.make_mesh()
    key = jax.random.PRNGKey(3)
    temps = arrays.geometric_temps(2.5, 0.05, 4)

    ba1, bk1, cv1 = pm.solve_on_mesh(
        m, jnp.asarray(seed), key, mesh, 2, 4, 50, engine="chain",
        temps=temps,
    )
    ba2, bk2, cv2 = pm.solve_lanes(
        arrays.stack_models([m]), mesh, 2, temps,
        lane_seeds=seed[None], keys=jnp.stack([key]), engine="chain",
        steps_per_round=50,
    )
    assert np.array_equal(np.asarray(ba1), np.asarray(ba2)[:, 0])
    assert np.array_equal(np.asarray(bk1), np.asarray(bk2)[:, 0])
    assert np.array_equal(np.asarray(cv1), np.asarray(cv2)[:, 0])


@pytest.mark.soak
@pytest.mark.slow  # ~20 s; nightly. Tier-1 keeps the same interpret-
# under-lane-vmap path via test_sharded_interpret_scorer_bit_parity
# (whose dl=1 base IS this dispatch).
def test_lane_vmap_interpret_scorer_parity():
    """The Pallas kernels under the lane vmap (interpret mode on CPU —
    the very code path the TPU runs) match the XLA scorer bit-for-bit."""
    inst = _adv_instance(7)
    m = arrays.from_instance(inst)
    seed = np.asarray(greedy_seed(inst), np.int32)
    mesh = pm.make_mesh()
    temps = arrays.geometric_temps(2.0, 0.02, 8)
    ms = arrays.stack_models([m, m])
    keys = jnp.stack([jax.random.PRNGKey(0), jax.random.PRNGKey(1)])
    lane_seeds = np.stack([seed, seed])
    o_x = pm.solve_lanes(ms, mesh, 2, temps, lane_seeds=lane_seeds,
                         keys=keys, engine="sweep", scorer="xla")
    o_p = pm.solve_lanes(ms, mesh, 2, temps, lane_seeds=lane_seeds,
                         keys=keys, engine="sweep",
                         scorer="pallas-interpret")
    assert np.array_equal(np.asarray(o_x[1]), np.asarray(o_p[1]))
    assert np.array_equal(np.asarray(o_x[2]), np.asarray(o_p[2]))


@pytest.mark.soak
@pytest.mark.slow  # ~18 s; nightly. Tier-1 keeps lane-vs-b1 parity at
# the mesh level (sweep + chain b1 pins) and the engine batch dispatch
# via test_engine_batch_parity_under_forced_split.
def test_solve_tpu_batch_matches_b1_lane_solves():
    """Engine-level contract: every lane of a B=3 batch returns exactly
    the plan its own B=1 lane solve returns (same bucket, same seeds)
    and every lane is feasible. (Closing to the exact move bound needs
    the full annealing budget — that is the bench throughput scenario's
    acceptance check, not this 16-round smoke's.)"""
    insts = [_adv_instance(s) for s in (7, 8, 9)]
    batched = solve_tpu_batch(insts, seeds=0, engine="sweep", batch=8,
                              rounds=16)
    for i, (inst, res) in enumerate(zip(insts, batched)):
        assert res.stats["lanes"] == 3 and res.stats["lane"] == i
        assert res.stats["feasible"], res.stats
        solo = solve_tpu_batch([inst], seeds=i, engine="sweep", batch=8,
                               rounds=16)[0]
        assert np.array_equal(res.a, solo.a), (
            f"lane {i} diverged from its B=1 solve"
        )


def test_solve_tpu_batch_unstackable_falls_back():
    """Lanes whose broker/rack axes differ cannot stack; the batch API
    still returns correct per-instance solves, tagged as fallbacks."""
    a = _adv_instance(7)
    b = _adv_instance(7, n_brokers=48, n_topics_low=4, n_topics_high=4)
    out = solve_tpu_batch([a, b], seeds=0, rounds=8, batch=8)
    assert len(out) == 2
    for res in out:
        assert res.stats.get("lane_fallback")
        assert res.stats["feasible"]


def test_solve_tpu_batch_mixed_sizes_share_bucket():
    """Different partition counts inside one batch pad up to ONE common
    bucket; every lane stays feasible and its plan decodes to its own
    instance's shape."""
    a = _adv_instance(7)
    sc = gen.adversarial(n_brokers=32, n_topics_low=3, n_topics_high=3,
                         parts_per_topic=9, seed=11)
    b = build_instance(sc.current, sc.broker_list, sc.topology)
    out = solve_tpu_batch([a, b], seeds=0, engine="sweep", batch=8,
                          rounds=16)
    assert out[0].stats["bucket_parts"] == out[1].stats["bucket_parts"]
    assert out[0].a.shape == (a.num_parts, a.max_rf)
    assert out[1].a.shape == (b.num_parts, b.max_rf)
    for inst, res in zip((a, b), out):
        assert res.stats["feasible"]
