"""Pallas scoring-kernel parity (SURVEY.md §7 hard part 3): the tiled
kernel must agree exactly — integer-for-integer — with the pure-XLA scorer
and the numpy oracle, across batch sizes, non-tile-aligned partition
counts, variable RF, and infeasible candidates. Runs in interpret mode on
the CPU mesh; the same kernel compiles natively on TPU."""

import numpy as np
import pytest
import jax.numpy as jnp

from kafka_assignment_optimizer_tpu import build_instance
from kafka_assignment_optimizer_tpu.ops.score import score_batch
from kafka_assignment_optimizer_tpu.ops.score_pallas import score_batch_pallas
from kafka_assignment_optimizer_tpu.solvers.tpu import arrays

from tests.test_tpu_engine import random_cluster


@pytest.mark.parametrize("case", [
    dict(n_brokers=12, n_parts=20, rf=3, n_racks=3, drop=1),
    dict(n_brokers=8, n_parts=7, rf=2, n_racks=2, drop=0),   # P < tile
    dict(n_brokers=20, n_parts=33, rf=4, n_racks=5, drop=2),  # odd P
    dict(n_brokers=6, n_parts=9, rf=1, n_racks=2, drop=0),   # RF=1 edge
])
def test_pallas_scorer_matches_xla(case, rng):
    current, brokers, topo = random_cluster(rng, **case)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    n = 5
    a = rng.integers(
        0, inst.num_brokers, size=(n, *inst.a0.shape)
    ).astype(np.int32)
    ref = score_batch(jnp.asarray(a), m)
    got = score_batch_pallas(jnp.asarray(a), m, interpret=True)
    np.testing.assert_array_equal(np.asarray(got.weight), np.asarray(ref.weight))
    np.testing.assert_array_equal(np.asarray(got.pen_broker), np.asarray(ref.pen_broker))
    np.testing.assert_array_equal(np.asarray(got.pen_leader), np.asarray(ref.pen_leader))
    np.testing.assert_array_equal(np.asarray(got.pen_rack), np.asarray(ref.pen_rack))
    np.testing.assert_array_equal(
        np.asarray(got.pen_part_rack), np.asarray(ref.pen_part_rack)
    )
    np.testing.assert_array_equal(np.asarray(got.cnt), np.asarray(ref.cnt))
    np.testing.assert_array_equal(np.asarray(got.lcnt), np.asarray(ref.lcnt))
    np.testing.assert_array_equal(np.asarray(got.rcnt), np.asarray(ref.rcnt))


def test_pallas_scorer_matches_numpy_oracle(rng):
    """Transitively: kernel == XLA == numpy; assert the endpoints too."""
    current, brokers, topo = random_cluster(rng, 10, 15, 2, 2, drop=1)
    inst = build_instance(current, brokers, topo)
    m = arrays.from_instance(inst)
    a = rng.integers(0, inst.num_brokers, size=(3, *inst.a0.shape)).astype(np.int32)
    got = score_batch_pallas(jnp.asarray(a), m, interpret=True)
    for i in range(a.shape[0]):
        v = inst.violations(a[i])
        assert int(got.weight[i]) == inst.preservation_weight(a[i])
        assert int(got.pen_broker[i]) == v["broker_balance"]
        assert int(got.pen_leader[i]) == v["leader_balance"]
        assert int(got.pen_rack[i]) == v["rack_balance"]
        assert int(got.pen_part_rack[i]) == v["part_rack_diversity"]
