"""Property fuzz (SURVEY.md §4.3, widened in r2): random clusters across
the full input space — multiple topics, per-topic RF, unequal racks,
broker add AND remove, RF changes — through the full ``optimize`` stack.
Every emitted plan must satisfy C4–C10 exactly (the report's violation
counts are computed by the numpy oracle, the ground truth)."""

from __future__ import annotations

import numpy as np
import pytest

from kafka_assignment_optimizer_tpu import optimize
from kafka_assignment_optimizer_tpu.models.cluster import (
    Assignment,
    PartitionAssignment,
    Topology,
)
# THE messy generator lives in gen (docs/PORTFOLIO.md): the bench
# portfolio A/B consumes the same stream, so 'messy[1] was the tier-1
# xfail' can never silently desynchronize from what bench measures
from kafka_assignment_optimizer_tpu.utils.gen import (
    messy_cluster as random_messy_cluster,
)

# soak tier (VERDICT r4 item 5): the property fuzz sweeps many random
# clusters through full solves — release gate, not commit gate
pytestmark = pytest.mark.soak


@pytest.mark.parametrize("case_seed", range(8))
def test_random_messy_clusters_all_constraints_hold(case_seed):
    rng = np.random.default_rng(1000 + case_seed)
    current, brokers, topo, target_rf = random_messy_cluster(rng)
    max_rf = max(len(p.replicas) for p in current.partitions)
    want_rf = target_rf or max_rf
    if want_rf > len(brokers):
        pytest.skip("RF exceeds broker count — rejected by the model")
    res = optimize(current, brokers, topo, target_rf=target_rf,
                   solver="tpu", batch=16, rounds=12,
                   steps_per_round=300, seed=case_seed)
    rep = res.report()
    assert rep["feasible"], rep["violations"]
    got = {(p.topic, p.partition): p.replicas
           for p in res.assignment.partitions}
    for p in current.partitions:
        reps = got[(p.topic, p.partition)]
        rf = target_rf or len(p.replicas)
        assert len(reps) == rf, (p.topic, p.partition, reps)
        assert len(set(reps)) == rf  # per-broker uniqueness
        assert set(reps) <= set(brokers)  # eligibility


@pytest.mark.parametrize("case_seed", [
    0,
    # seed 1 builds an EXACT-band instance: reaching feasibility needs
    # a coordinated 2-move exchange whose intermediate state adds a
    # violation — at LAMBDA=64 a single default chain can never accept
    # it. Closed by PR 11 (docs/PORTFOLIO.md, docs/ANALYSIS.md): the
    # compound 2-move exchange evaluates the pair atomically, and the
    # portfolio races diverse (lam, temp_scale) lanes — the winning
    # low-lam lane tunnels where the default lane froze. Previously a
    # triaged xfail; now a pass the portfolio must keep.
    1,
    # case 2 is the expensive draw (~12 s); it re-tiers to the nightly
    # soak run, cases 0/1/3 keep the shape coverage in tier-1
    pytest.param(2, marks=[pytest.mark.soak, pytest.mark.slow]),
    3,
])
def test_sweep_engine_on_messy_clusters(case_seed):
    """Force the at-scale engine onto irregular small instances — the
    shapes it never sees in production are where padding/rounding bugs
    hide (odd partition counts vs the 2-way pairing, rf=1 rows, unequal
    racks vs the kernel's K+1 null-rack algebra)."""
    rng = np.random.default_rng(2000 + case_seed)
    current, brokers, topo, target_rf = random_messy_cluster(rng)
    max_rf = max(len(p.replicas) for p in current.partitions)
    if (target_rf or max_rf) > len(brokers):
        pytest.skip("RF exceeds broker count")
    res = optimize(current, brokers, topo, target_rf=target_rf,
                   solver="tpu", engine="sweep", batch=8, rounds=32,
                   seed=case_seed)
    assert res.report()["feasible"], res.report()["violations"]


@pytest.mark.soak
@pytest.mark.slow  # ~17 s; nightly with the rest of the fuzz tier.
# Tier-1 keeps the XLA-path messy-cluster cases and the kernel parity
# pins in test_sweep.py/test_mesh_sharding.py.
def test_sweep_engine_kernel_path_on_messy_cluster():
    """The Mosaic code paths (interpret mode) on an irregular instance:
    same plan as the XLA path, byte-for-byte."""
    rng = np.random.default_rng(3000)
    current, brokers, topo, target_rf = random_messy_cluster(rng)
    max_rf = max(len(p.replicas) for p in current.partitions)
    if (target_rf or max_rf) > len(brokers):  # pragma: no cover - seed-dep
        pytest.skip("RF exceeds broker count")
    import jax
    import jax.numpy as jnp

    from kafka_assignment_optimizer_tpu import build_instance
    from kafka_assignment_optimizer_tpu.solvers.tpu import arrays
    from kafka_assignment_optimizer_tpu.solvers.tpu.arrays import (
        geometric_temps,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed
    from kafka_assignment_optimizer_tpu.solvers.tpu.sweep import (
        make_sweep_solver_fn,
    )

    inst = build_instance(current, brokers, topo, target_rf)
    m = arrays.from_instance(inst)
    seed = jnp.asarray(greedy_seed(inst), jnp.int32)
    temps = geometric_temps(2.0, 0.02, 12)
    outs = {}
    for scorer in ("xla", "pallas-interpret"):
        solve = jax.jit(make_sweep_solver_fn(n_chains=4, scorer=scorer))
        ba, bk, _ = solve(m, seed, jax.random.PRNGKey(1), temps)
        outs[scorer] = (np.asarray(ba), int(bk))
    np.testing.assert_array_equal(outs["xla"][0],
                                  outs["pallas-interpret"][0])
    assert outs["xla"][1] == outs["pallas-interpret"][1]


def test_mixed_rf_lopsided_racks_band_not_inverted():
    """r2 review reproduction: a tiny rack whose forced minimum (from
    many rf=K partitions) exceeds its proportional ceiling must get the
    ceiling RAISED, not an inverted [lo > hi] band that makes every
    instance bound-infeasible by construction."""
    from kafka_assignment_optimizer_tpu import build_instance

    parts = []
    for p in range(10):  # rf=3 over 3 racks: 1 replica forced per rack
        parts.append(PartitionAssignment("t3", p, [0, 1, 9]))
    for p in range(100):  # rf=1 filler drives the proportional shares up
        parts.append(PartitionAssignment("t1", p, [1 + (p % 16)]))
    rack_of = {0: "a"}
    rack_of.update({b: "b" for b in range(1, 9)})
    rack_of.update({b: "c" for b in range(9, 17)})
    inst = build_instance(Assignment(partitions=parts), list(range(17)),
                          Topology(rack_of=rack_of))
    assert (inst.rack_lo <= inst.rack_hi).all(), (
        inst.rack_lo, inst.rack_hi
    )
    # and the bands admit a plan: the exact solver must find one
    res = optimize(Assignment(partitions=parts), list(range(17)),
                   Topology(rack_of=rack_of), solver="milp")
    assert res.report()["feasible"]
