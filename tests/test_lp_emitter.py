"""Emitter parity tests (SURVEY.md §4.2): regenerate the reference LP
sample's structure and assert section order, row counts (SURVEY.md §3.3),
variable naming, and bound arithmetic (README.md:144-185)."""

import re

import numpy as np

from kafka_assignment_optimizer_tpu import build_instance
from kafka_assignment_optimizer_tpu.solvers.lp import emit_lp, var_name


def test_var_naming_matches_reference(demo):
    current, brokers, topo = demo
    inst = build_instance(current, brokers, topo)
    # README.md:146 style: t{topicIdx}b{brokerId}p{partitionId}[_l]
    b9 = int(np.searchsorted(inst.broker_ids, 9))
    assert var_name(inst, 6, b9, False) == "t1b9p6"
    assert var_name(inst, 6, b9, True) == "t1b9p6_l"


def test_lp_text_structure(demo):
    current, brokers, topo = demo
    inst = build_instance(current, brokers, topo)
    text = emit_lp(inst)
    P, B, K = inst.num_parts, inst.num_brokers, inst.num_racks

    # section headers present, in the reference order (README.md:144-185);
    # rack sections repeat per rack / per (partition, rack) with the name
    # suffix the sample shows ("... per racks. tor02 here", README.md:173,
    # "... p0 on tor02 here", README.md:178)
    headers = [ln for ln in text.splitlines() if ln.startswith("//")]
    assert headers == [
        "// Optimization function, based on current assignment ",
        "// Constrain on replication factor for every partition",
        "// Constraint on having one and only one leader per partition",
        "// Constraint on min/max replicas per broker",
        "// Constraint on min/max leaders per broker",
        "// Constraint on no leader and replicas on the same broker",
        *[f"// Constrain on min/max total replicas per racks. {r} here"
          for r in inst.rack_names],
        *[f"// Constrain on min/max replicas per partitions per racks. "
          f"p{p} on {r} here"
          for p in range(P) for r in inst.rack_names],
        "// All variables are binary",
    ]

    # row counts per SURVEY.md §3.3: P + P + 2B + 2B + BP + 2K + PK
    rows = [ln for ln in text.splitlines() if ln.endswith(";") and "max:" not in ln
            and not ln.startswith("t1b1p0,")]
    n_constraints = len([r for r in rows if ("<=" in r or ">=" in r or "=" in r)])
    assert n_constraints == P + P + 2 * B + 2 * B + B * P + 2 * K + P * K

    # objective line: starts max:, weights drawn from the observed tiers
    obj = next(ln for ln in text.splitlines() if ln.startswith("max:"))
    coeffs = set(re.findall(r"(\d) t1b\d+p\d+", obj))
    assert coeffs <= {"1", "2", "4"}
    assert "4 " in obj  # leader-keep tier present

    # bin block declares the full cross product: 2*B*P names
    bin_idx = text.splitlines().index("bin")
    bin_line = text.splitlines()[bin_idx + 1]
    assert bin_line.count(",") + 1 == 2 * B * P
    assert bin_line.endswith(";")


def test_lp_bounds_in_rows(demo):
    current, brokers, topo = demo
    inst = build_instance(current, brokers, topo)
    text = emit_lp(inst)
    lines = text.splitlines()
    # broker band rows: <= 2 then >= 1 (20 replicas / 19 brokers, README.md:158-161)
    start = lines.index("// Constraint on min/max replicas per broker")
    assert lines[start + 1].endswith("<= 2;")
    assert lines[start + 2].endswith(">= 1;")
    # leader band rows: <= 1 then >= 0 (README.md:163-166)
    start = lines.index("// Constraint on min/max leaders per broker")
    assert lines[start + 1].endswith("<= 1;")
    assert lines[start + 2].endswith(">= 0;")
    # uniqueness rows: pairs x + y <= 1 (README.md:168-171)
    start = lines.index("// Constraint on no leader and replicas on the same broker")
    assert re.fullmatch(r"t1b0p0 \+ t1b0p0_l <= 1;", lines[start + 1])


def test_lp_parse_round_trip(demo):
    # feed a synthetic lp_solve -S4 listing through the parser
    from kafka_assignment_optimizer_tpu.solvers.lp import parse_lp_solve_output
    from kafka_assignment_optimizer_tpu.solvers.milp import solve_milp

    current, brokers, topo = demo
    inst = build_instance(current, brokers, topo)
    res = solve_milp(inst)
    lines = ["Value of objective function: whatever", ""]
    for p in range(inst.num_parts):
        for s in range(int(inst.rf[p])):
            b = int(res.a[p, s])
            lines.append(f"{var_name(inst, p, b, s == 0)}   1")
    # zeros listed too, as lp_solve does
    lines.append("t1b0p0    0")
    a = parse_lp_solve_output(inst, "\n".join(lines))
    np.testing.assert_array_equal(np.sort(a, 1), np.sort(res.a, 1))
    assert (a[:, 0] == res.a[:, 0]).all()
