"""Continuous roofline observatory tests (ISSUE 18,
docs/OBSERVABILITY.md "Reading a roofline"):

- every tier-1 solve path (sync, pipelined, megachunk, portfolio,
  batch lanes, decomposed) lands a wall-clock attribution ledger whose
  components sum to wall within epsilon;
- cost models are captured ONCE per compile and warm re-solves reuse
  them with zero recomputation;
- the profiler's own overhead stays under 2% of solve wall;
- the ``/debug/profile`` + ``/metrics`` surfaces and the offline
  ``kao-prof`` CLI render the same aggregation;
- the regress efficiency gate: self-compare stays clean, a seeded
  occupancy collapse trips the regression verdict with walls untouched.
"""

from __future__ import annotations

import json

import pytest

from kafka_assignment_optimizer_tpu import build_instance
from kafka_assignment_optimizer_tpu.api import optimize
from kafka_assignment_optimizer_tpu.models.cluster import (
    demo_assignment,
    demo_broker_list,
    demo_topology,
)
from kafka_assignment_optimizer_tpu.obs import flight as oflight
from kafka_assignment_optimizer_tpu.obs import prof as oprof
from kafka_assignment_optimizer_tpu.obs import regress as oregress
from kafka_assignment_optimizer_tpu.solvers.tpu.engine import (
    solve_tpu,
    solve_tpu_batch,
)
from kafka_assignment_optimizer_tpu.utils import gen


def _adv_instance(seed: int):
    sc = gen.adversarial(n_brokers=32, n_topics_low=3, n_topics_high=3,
                         parts_per_topic=10, seed=seed)
    return build_instance(sc.current, sc.broker_list, sc.topology)


def _assert_ledger_sums(led: dict) -> None:
    """The sums-to-wall invariant: every component (queue wait through
    unattributed other) adds up to the ledger wall within epsilon plus
    the 4-decimal rounding slack of 8 fields."""
    assert isinstance(led, dict), led
    assert led["ok"] is True, led
    total = sum(led[f] for f in oprof.LEDGER_FIELDS)
    eps = max(0.005, 0.01 * led["wall_s"]) + 0.001
    assert abs(total - led["wall_s"]) <= eps, (total, led)


@pytest.fixture(scope="module")
def solved():
    """One pass over every ledger-bearing solve path, sharing warm
    executables with the rest of the tier-1 run; each test then reads
    the flight records and profiler state this pass produced."""
    demo = (demo_assignment(), demo_broker_list(), demo_topology())
    cur, brk, topo = demo
    out: dict = {"demo": demo}
    ov0 = oprof.overhead()["seconds_total"]
    wall_total = 0.0

    oflight.reset_recent()
    r = optimize(cur, brk, topo, solver="tpu", engine="sweep", seed=0,
                 batch=8, rounds=8, steps_per_round=60, trace=True)
    out["sync"] = (r, oflight.recent(kind="solve")[-1])
    wall_total += r.solve.wall_clock_s

    r = optimize(cur, brk, topo, solver="tpu", engine="sweep", seed=0,
                 batch=8, rounds=16, steps_per_round=60, pipeline=True)
    out["pipelined"] = (r, oflight.recent(kind="solve")[-1])
    wall_total += r.solve.wall_clock_s

    r = optimize(cur, brk, topo, solver="tpu", engine="sweep", seed=0,
                 batch=8, rounds=32, steps_per_round=60, megachunk=8)
    out["mega"] = (r, oflight.recent(kind="solve")[-1])
    wall_total += r.solve.wall_clock_s

    res = solve_tpu(_adv_instance(21), seed=0, engine="sweep", batch=8,
                    rounds=8, portfolio=True)
    out["portfolio"] = (res, oflight.recent(kind="solve")[-1])
    wall_total += res.wall_clock_s

    insts = [_adv_instance(s) for s in (22, 23)]
    batched = solve_tpu_batch(insts, seeds=0, engine="sweep", batch=8,
                              rounds=8)
    out["batch"] = (batched, oflight.recent(kind="lane"))
    wall_total += batched[0].wall_clock_s

    sc = gen.ultra_jumbo(seed=0, **gen.SMOKE_KWARGS["ultra_jumbo"])
    res = solve_tpu(build_instance(**sc.kwargs), seed=0,
                    decompose=True, rounds=6)
    out["decomposed"] = (res, oflight.recent(kind="solve")[-1])
    wall_total += res.wall_clock_s

    out["overhead_s"] = oprof.overhead()["seconds_total"] - ov0
    out["wall_total"] = wall_total
    return out


# --------------------------------------------------------------------------
# attribution ledgers: sums-to-wall across every solve path
# --------------------------------------------------------------------------


def test_ledger_sums_to_wall_sync(solved):
    led = solved["sync"][1]["ledger"]
    _assert_ledger_sums(led)
    # the retire-side device waits landed as a real leaf
    assert led["device_s"] > 0, led


def test_ledger_sums_to_wall_pipelined(solved):
    _assert_ledger_sums(solved["pipelined"][1]["ledger"])


def test_ledger_sums_to_wall_megachunk(solved):
    r, rec = solved["mega"]
    assert r.solve.stats["megachunk"]["k"] > 1  # the fused path ran
    _assert_ledger_sums(rec["ledger"])


def test_ledger_sums_to_wall_portfolio(solved):
    res, rec = solved["portfolio"]
    assert res.stats["portfolio"]["width"] >= 2
    _assert_ledger_sums(rec["ledger"])


def test_ledger_sums_to_wall_batch_lanes(solved):
    batched, lane_recs = solved["batch"]
    assert len(lane_recs) >= len(batched)
    walls = set()
    for rec in lane_recs[-len(batched):]:
        _assert_ledger_sums(rec["ledger"])
        walls.add(rec["ledger"]["wall_s"])
    # every lane's ledger wall is the SHARED batch wall
    assert len(walls) == 1, walls


def test_ledger_sums_to_wall_decomposed(solved):
    res, rec = solved["decomposed"]
    assert res.stats["decompose"]["subproblems"] >= 1
    _assert_ledger_sums(rec["ledger"])


def test_ledger_overrun_surfaced_not_clamped():
    """Components exceeding wall beyond epsilon: ok=False plus a
    profiler counter — the measured leaves are NEVER clamped to fit."""
    c0 = oprof.snapshot()["counters"]["ledger_overruns_total"]
    tok = oflight.start_accounting()
    oflight.note_window("device", 5.0)
    acc = oflight.end_accounting(tok)
    led = oflight._ledger(acc, 1.0)
    assert led["ok"] is False
    assert led["device_s"] == 5.0  # surfaced verbatim
    assert led["other_s"] == 0.0
    assert oprof.snapshot()["counters"]["ledger_overruns_total"] == c0 + 1


def test_attribute_nets_out_nested_leaf_windows():
    """A leaf window accrued INSIDE a nested attribution block is
    netted out of the block's category — no double counting by
    construction."""
    tok = oflight.start_accounting()
    with oflight.attribute("boundary"):
        oflight.note_window("device", 0.05)
    acc = oflight.end_accounting(tok)
    assert acc.seconds["device"] == pytest.approx(0.05)
    assert acc.seconds.get("boundary", 0.0) < 0.01


def test_queue_wait_contextvar_lands_and_resets():
    tok = oflight.set_queue_wait(0.25)
    try:
        led = oflight._ledger(None, 1.0)
    finally:
        oflight.reset_queue_wait(tok)
    assert led["queue_wait_s"] == 0.25
    assert led["wall_s"] == 1.25  # wall includes the queue hop
    assert oflight._ledger(None, 1.0)["queue_wait_s"] == 0.0


# --------------------------------------------------------------------------
# cost models: captured once per compile, reused warm
# --------------------------------------------------------------------------


def test_cost_models_captured_with_flops(solved):
    rows = oprof.snapshot()["executables"]
    assert rows, "no cost models captured across the solve pass"
    # XLA CPU provides flops; at least the dominant executables carry a
    # cost model with an achieved-occupancy column
    assert any(r.get("flops") for r in rows), rows
    assert any("occupancy_flops" in r or "occupancy_hbm" in r
               for r in rows), rows
    top = rows[0]  # sorted by device seconds: the dominant executable
    assert top["dispatches"] > 0 and top["device_s"] > 0


def test_warm_resolve_reuses_cached_cost_model(solved):
    """The capture is compile-time state: a warm re-solve must add
    ZERO captures while every dispatch reuses the cached analysis."""
    cur, brk, topo = solved["demo"]
    c0 = oprof.snapshot()["counters"]
    optimize(cur, brk, topo, solver="tpu", engine="sweep", seed=0,
             batch=8, rounds=8, steps_per_round=60)
    c1 = oprof.snapshot()["counters"]
    assert c1["captures_total"] == c0["captures_total"]
    assert c1["reuses_total"] > c0["reuses_total"]


def test_profiler_overhead_under_2pct_of_solve_wall(solved):
    assert solved["overhead_s"] < 0.02 * solved["wall_total"], solved[
        "overhead_s"]


# --------------------------------------------------------------------------
# dispatch-gap series from span timestamps
# --------------------------------------------------------------------------


def test_observe_gaps_histogram_and_exemplar():
    oprof.GAP_HIST.reset()
    report = {"spans": {
        "name": "ladder", "start_s": 0.0, "wall_s": 1.0, "spans": [
            {"name": "dispatch", "start_s": 0.0, "wall_s": 0.1},
            {"name": "chunk", "start_s": 0.1, "wall_s": 0.01},
            {"name": "dispatch", "start_s": 0.103, "wall_s": 0.1},
        ]}}
    oprof.observe_gaps(report, "trace-gap")
    snap = oprof.gap_snapshot()["ladder"]
    assert snap["count"] == 1
    assert snap["sum"] == pytest.approx(0.003)
    assert any(e["trace_id"] == "trace-gap"
               for e in oprof.gap_exemplars())


def test_solve_report_feeds_gap_histogram(solved):
    """record_solve derives the gap series from the traced solve's
    span timestamps (the sync fixture solve ran with trace=True)."""
    assert "ladder" in oprof.gap_snapshot()


# --------------------------------------------------------------------------
# surfaces: /debug/profile, /metrics, kao-prof CLI
# --------------------------------------------------------------------------


def test_debug_profile_handler_shape(solved):
    from kafka_assignment_optimizer_tpu import serve

    out = serve.handle_debug_profile()
    for k in ("peaks", "roofline", "executables", "attribution",
              "worst_solves", "dispatch_gaps", "counters", "overhead"):
        assert k in out, k
    assert out["attribution"], "no ledgers aggregated"
    for g in out["attribution"].values():
        assert abs(sum(g["shares"].values()) - 1.0) <= 0.02, g
    ws = out["worst_solves"]
    assert ws, "no worst-attribution solves"
    # ranked by lost (non-device) wall, descending
    lost = [w["lost_s"] for w in ws]
    assert lost == sorted(lost, reverse=True)
    assert out["roofline"], "no per-bucket roofline groups"


def test_metrics_exposition_has_prof_families(solved):
    from kafka_assignment_optimizer_tpu import serve

    text = serve.render_metrics()
    assert "kao_prof_captures_total" in text
    assert "# TYPE kao_prof_occupancy gauge" in text
    assert "kao_prof_device_seconds_total{" in text
    assert "kao_prof_dispatch_gap_seconds_bucket" in text


def test_kao_prof_cli_over_flight_dir(tmp_path, capsys):
    rec = oflight.FlightRecorder()
    rec.configure(str(tmp_path))
    led = {"wall_s": 1.0, "queue_wait_s": 0.0, "constructor_s": 0.2,
           "compile_s": 0.0, "dispatch_gap_s": 0.1, "device_s": 0.5,
           "transfer_s": 0.0, "boundary_s": 0.1, "other_s": 0.1,
           "ok": True}
    for i in range(3):
        rec.write({"ts": float(i), "kind": "solve", "wall_s": 1.0,
                   "trace_id": f"t{i}", "seq": i, "ledger": dict(led)})
    rc = oprof.main([str(tmp_path), "--json", "--top", "2"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["records"] == 3
    g = out["attribution"]["solve"]
    assert g["solves"] == 3 and g["ok"] == 3
    assert g["shares"]["device_s"] == pytest.approx(0.5, abs=0.01)
    assert len(out["worst_solves"]) == 2
    assert out["worst_solves"][0]["lost_s"] == pytest.approx(0.5)


def test_kao_prof_cli_unreadable_source_is_loud(tmp_path, capsys):
    assert oprof.main([str(tmp_path / "missing")]) == 2
    assert "kao-prof" in capsys.readouterr().err


# --------------------------------------------------------------------------
# the regress efficiency gate
# --------------------------------------------------------------------------


def _prof_artifact() -> dict:
    return {
        "metric": "decommission_255b_10000p_warm_wall_clock",
        "value": 1.0, "unit": "s",
        "platform": "cpu", "cold_wall_clock_s": 2.0,
        "moves": 117, "min_moves_lb": 117, "feasible": True,
        "proved_optimal": True,
        "env": {"git_sha": "aaaa000000", "platform": "cpu",
                "devices": 8, "xla_flags": ""},
        "profile": {
            "path": "lanes", "flops": 2.5e9, "bytes_accessed": 1.0e9,
            "occupancy_flops": 0.04, "occupancy_hbm": 0.15,
            "occupancy_hbm_p50": 0.14, "occupancy_hbm_p99": 0.18,
            "dispatches": 64, "device_s": 0.5, "device_share": 0.5,
            "ledger_shares": {"device_s": 0.5, "other_s": 0.1},
            "ledger_ok": True,
        },
    }


def test_regress_profile_self_compare_is_clean():
    art = _prof_artifact()
    v = oregress.compare(art, json.loads(json.dumps(art)))
    assert v["comparable"] and v["verdict"] == "ok", v


def test_regress_seeded_occupancy_drop_trips_with_walls_flat():
    """The efficiency axis the latency quorum cannot see: occupancy
    halves, every wall stays identical, and the gate still trips —
    through the confirmed profile.*_collapse check."""
    art = _prof_artifact()
    drop = oregress.seed_occupancy_drop(art, 2.0)
    assert drop["value"] == art["value"]
    assert drop["cold_wall_clock_s"] == art["cold_wall_clock_s"]
    assert drop["profile"]["occupancy_hbm"] == pytest.approx(0.075)
    v = oregress.compare(art, drop)
    assert v["verdict"] == "regression", v
    mets = [q["metric"] for q in v["quality_regressions"]]
    assert "profile.occupancy_hbm_collapse" in mets
    assert "profile.occupancy_flops_collapse" in mets


def test_regress_ledger_ok_flip_is_deterministic_regression():
    art = _prof_artifact()
    bad = json.loads(json.dumps(art))
    bad["profile"]["ledger_ok"] = False
    v = oregress.compare(art, bad)
    assert v["verdict"] == "regression"
    assert any(q["metric"] == "profile.ledger_ok"
               for q in v["quality_regressions"])


def test_regress_slowdown_fixture_scales_occupancy_too():
    """A uniform 2x slowdown stretches every device window, so the
    seeded-slowdown fixture must halve achieved occupancy — keeping
    the two CI trip-wires consistent with physics."""
    slow = oregress.seed_slowdown(_prof_artifact(), 2.0)
    assert slow["profile"]["occupancy_hbm"] == pytest.approx(0.075)
    assert slow["profile"]["occupancy_flops"] == pytest.approx(0.02)


# --------------------------------------------------------------------------
# bench artifact carries the profile block
# --------------------------------------------------------------------------


def test_bench_profile_block_from_live_state(solved):
    import bench as bench_mod

    blk = bench_mod._profile_block()
    assert blk, "no profile block despite live observatory state"
    prof = blk["profile"]
    assert prof.get("dispatches", 0) > 0
    assert "ledger_ok" in prof
    assert "device_share" in prof and 0.0 <= prof["device_share"] <= 1.0
    assert abs(sum(prof["ledger_shares"].values()) - 1.0) <= 0.02
