#!/usr/bin/env python
"""Benchmark harness — the north-star scenario (BASELINE.json).

Runs the headline configuration (256 brokers / 8 racks / 10k partitions /
RF=3, single-broker decommission) through the TPU annealing backend and
prints ONE JSON line:

    {"metric": ..., "value": <warm_wall_clock_s>, "unit": "s",
     "vs_baseline": ..., "platform": ..., "cold_wall_clock_s": ...}

``vs_baseline`` is the speed-up vs the north-star budget of 5 s
(BASELINE.json: "<= lp_solve's move count in <5s wall-clock"), gated on
plan quality: if the plan is infeasible, or moves exceed the provable
minimum (the replicas hosted by the decommissioned broker), vs_baseline is
reported as 0.0 — a fast wrong answer scores nothing.

Robustness contract (round-1 postmortem): the site TPU plugin ("axon")
can fail init with UNAVAILABLE *or hang for minutes*. This harness
therefore never imports jax in the parent process. It probes backend
init in a subprocess under a hard timeout, falls back to
``JAX_PLATFORMS=''`` (automatic) and then ``cpu``, runs each scenario in
a child process under a timeout, and ALWAYS prints the one-line JSON —
on total failure the line carries ``"error"`` and ``vs_baseline: 0.0``.

By default every BASELINE scenario runs (plus the adversarial and jumbo
stretch configs) and the one stdout JSON line carries a compact
``scenarios`` array of positional rows (field order in ``ROW_SCHEMA``),
so the driver artifact evidences the complete results table, not just
the headline (VERDICT r2 item 3). The line is kept under
``STDOUT_BUDGET`` bytes — the driver records only a ~2000-char stdout
tail (r3 item 1) — with the full per-scenario detail on stderr. After the warm headline runs, one more
FRESH child process re-solves the headline against the now-populated
persistent compile cache and reports ``cold_cached_wall_clock_s`` — the
cold number a second process on the same host actually pays.

Flags: ``--scenario`` picks another headline, ``--headline-only``
skips the side scenarios, ``--smoke`` shrinks the instances for quick
CPU checks, ``--kernel`` additionally times the Pallas scoring kernel
vs the XLA scorer (TPU only).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

BASELINE_BUDGET_S = 5.0  # north-star (BASELINE.json)

# solve pipeline phases, in execution order — the positional layout of
# the per-scenario phase_s column (from the engine's solve reports)
PHASE_ORDER = ("bounds", "constructor", "seed", "ladder", "polish",
               "verify")

# constructor sub-phases (ISSUE 10, docs/CONSTRUCTOR.md): their summed
# seconds are the scenario row's construct_host_s column — the host
# time actually spent in the flow bounds / greedy / reseat / adoption
# loops the vectorized constructor rewrote, as opposed to the
# constructor PHASE span, which is mostly overlap-wait
SUB_PHASES = ("bounds_flow", "greedy", "reseat", "adopt")


def _median(xs) -> float | None:
    """Rounded median, delegating to the ONE median implementation the
    comparator uses (obs/regress.py) so the stamped artifact medians
    can never diverge from the values ``--compare`` recomputes.
    Import is lazy and parent-safe: regress touches no jax."""
    from kafka_assignment_optimizer_tpu.obs.regress import _median as _m

    v = _m(xs or ())
    return None if v is None else round(v, 4)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:  # malformed override must not kill the harness
        print(f"[bench] ignoring malformed {name}", file=sys.stderr)
        return default


PROBE_TIMEOUT_S = _env_float("KAO_PROBE_TIMEOUT", 240.0)
CHILD_TIMEOUT_S = _env_float("KAO_BENCH_TIMEOUT", 1800.0)

# config-level pinning, not just the env var: the site accelerator hook
# wraps backend lookup and can override JAX_PLATFORMS unless the config is
# set explicitly (same reason utils.platform.pin_platform exists)
_PROBE_CODE = (
    "import os, jax\n"
    "w = os.environ.get('JAX_PLATFORMS')\n"
    "if w: jax.config.update('jax_platforms', w)\n"
    "print('PLATFORM=' + jax.devices()[0].platform)\n"
    "print('NDEV=' + str(jax.device_count()))\n"
)


# --------------------------------------------------------------------------
# parent side: backend probing + child orchestration (never imports jax)
# --------------------------------------------------------------------------

def _probe(env: dict, timeout: float) -> tuple[str | None, int | None,
                                               str | None]:
    """Try backend init in a subprocess. Returns
    (platform, device_count, error)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            env=env, timeout=timeout, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return None, None, f"backend init timed out after {timeout:.0f}s"
    except OSError as e:  # pragma: no cover - exec failure
        return None, None, f"probe exec failed: {e}"
    if r.returncode == 0:
        plat = ndev = None
        for line in r.stdout.splitlines():
            if line.startswith("PLATFORM="):
                plat = line.split("=", 1)[1].strip()
            elif line.startswith("NDEV="):
                try:
                    ndev = int(line.split("=", 1)[1].strip())
                except ValueError:
                    pass
        if plat is not None:
            return plat, ndev, None
        return None, None, "probe printed no platform"
    tail = (r.stderr or r.stdout or "").strip().splitlines()
    return None, None, (
        " | ".join(tail[-3:])[-500:] or f"probe rc={r.returncode}"
    )


def resolve_backend() -> tuple[dict, str, str | None, int | None]:
    """Pick an environment whose jax backend provably initializes.

    Attempt order: env as-is (site plugin may provide TPU), then
    ``JAX_PLATFORMS=''`` (automatic choice, tolerates plugin failure),
    then ``cpu`` (assumed always available). Returns
    (env, platform, tpu_error, device_count) where tpu_error records
    why an accelerator was NOT used, if so.
    """
    # attempt order, deduplicated: "env as-is" and "automatic" are the
    # same probe when JAX_PLATFORMS is unset/empty — don't hang twice
    attempts: list[str | None] = [None]
    if os.environ.get("JAX_PLATFORMS"):
        attempts.append("")
    first_err: str | None = None
    for override in attempts:
        env = dict(os.environ)
        if override is not None:
            env["JAX_PLATFORMS"] = override
        plat, ndev, err = _probe(env, PROBE_TIMEOUT_S)
        if plat is not None:
            return env, plat, first_err if plat == "cpu" else None, ndev
        first_err = first_err or err
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # terminal fallback
    # probe the fallback too: when even CPU init is broken (bad jaxlib,
    # truncated venv) the harness must say so in the one JSON line with
    # an explicit platform field, not die mid-run in every child
    plat, ndev, err = _probe(env, PROBE_TIMEOUT_S)
    if plat is None:
        first_err = first_err or err
    return env, plat or "cpu", first_err, ndev


def _env_stamp(platform: str, ndev: int | None, env: dict) -> dict:
    """The comparability stamp (ISSUE 9 satellite): git SHA, device
    count, platform and XLA_FLAGS travel IN the artifact so
    ``obs/regress.py`` can refuse to compare numbers from incomparable
    environments instead of reporting a bogus regression."""
    sha = None
    try:
        r = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        )
        if r.returncode == 0:
            sha = r.stdout.strip()[:12] or None
    except (OSError, subprocess.TimeoutExpired):
        pass
    # process topology (ISSUE 19 satellite): a 1-host artifact must
    # never trip a bogus regression against a 2-host one, and a mesh
    # with a different (chains x lanes) split is a different machine as
    # far as per-dispatch numbers go. Best-effort: the parent process
    # may not have jax importable/initialized — the stamp then carries
    # the single-process defaults, which is exactly what the children
    # run with.
    n_procs, proc_idx = 1, 0
    mesh_axes = None
    jax = sys.modules.get("jax")  # never force the import: the parent
    try:                          # probes platforms via children only
        if jax is not None:
            n_procs = int(jax.process_count())
            proc_idx = int(jax.process_index())
            from kafka_assignment_optimizer_tpu.parallel.mesh import (
                mesh_snapshot,
            )

            mesh_axes = dict(mesh_snapshot()["axes"])
    except Exception:
        pass
    return {
        "git_sha": sha,
        "platform": platform,
        "devices": ndev,
        "xla_flags": env.get("XLA_FLAGS", ""),
        "n_processes": n_procs,
        "process_index": proc_idx,
        "mesh_axes": mesh_axes,
    }


def _run_child(
    args: argparse.Namespace, name: str, env: dict, warmrun: bool,
    kernel: bool = False, batch_bench: bool = False,
    replay_day: bool = False, portfolio_bench: bool = False,
    rollout_bench: bool = False, decompose_bench: bool = False,
    mesh_bench: bool = False,
) -> tuple[dict | None, str | None]:
    """Run one scenario in a child process; returns (result, error)."""
    cmd = [
        sys.executable, os.path.abspath(__file__), "--child",
        "--scenario", name, "--seed", str(args.seed),
    ]
    if args.smoke:
        cmd.append("--smoke")
    if warmrun:
        cmd.append("--warm")
    if batch_bench:
        cmd.append("--batch-bench")
    if replay_day:
        cmd.append("--replay-day")
    if portfolio_bench:
        cmd.append("--portfolio-bench")
    if rollout_bench:
        cmd.append("--rollout-bench")
    if decompose_bench:
        cmd.append("--decompose-bench")
    if mesh_bench:
        cmd.append("--mesh-bench")
    if args.kernel and kernel:
        # the kernel micro-bench is headline-only: other children would
        # burn minutes producing output that is never emitted
        cmd.append("--kernel")
    try:
        r = subprocess.run(
            cmd, env=env, timeout=CHILD_TIMEOUT_S, capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return None, f"scenario '{name}' timed out after {CHILD_TIMEOUT_S:.0f}s"
    for line in reversed(r.stdout.splitlines()):
        if line.startswith("RESULT "):
            try:
                return json.loads(line[len("RESULT "):]), None
            except json.JSONDecodeError as e:
                return None, f"unparsable child result: {e}"
    tail = (r.stderr or r.stdout or "").strip().splitlines()
    return None, " | ".join(tail[-4:])[-600:] or f"child rc={r.returncode}"


# --------------------------------------------------------------------------
# child side: actually solve (runs with a known-good JAX_PLATFORMS)
# --------------------------------------------------------------------------

def run_scenario(name: str, smoke: bool, seed: int, warm: bool) -> dict:
    from kafka_assignment_optimizer_tpu.utils.platform import pin_platform

    pin_platform()
    import jax

    from kafka_assignment_optimizer_tpu.api import optimize
    from kafka_assignment_optimizer_tpu.utils import gen

    # device-occupancy sampler (obs.sampler, docs/OBSERVABILITY.md):
    # --sample-devices threads KAO_SAMPLE_DEVICES into this child so
    # the headline row carries the measured duty cycle / HBM occupancy
    # and the sampler's OWN overhead accounting alongside the walls
    sampler = None
    if os.environ.get("KAO_SAMPLE_DEVICES"):
        from kafka_assignment_optimizer_tpu.obs.sampler import SAMPLER

        try:
            SAMPLER.configure(float(os.environ["KAO_SAMPLE_DEVICES"]))
            sampler = SAMPLER
        except ValueError:
            pass

    if smoke:
        sc = gen.SCENARIOS[name](**gen.SMOKE_KWARGS[name])
    else:
        sc = gen.SCENARIOS[name]()

    # the adversarial rows are the at-scale SEARCH-ENGINE benchmark
    # (VERDICT r3 item 2): the explicit engine knob opts out of the
    # host-side constructor/reseat races, so the sweep annealer must
    # close to the bound ladder ON-CHIP. The default (knob-free) path
    # wins the greedy+reseat race instead — measured separately below
    # and reported as default_wall_clock_s in the stderr detail.
    knobs = {"engine": "sweep"} if name in ("adversarial", "adv50k") else {}
    from kafka_assignment_optimizer_tpu.solvers.tpu import bucket

    cache0 = bucket.STATS.snapshot()
    walls = []
    # warm: runs 2..3 reuse the jit cache; report the best warm run —
    # the tunnel-attached TPU shows multi-second scheduler noise between
    # identical solves (r2: 3.2 s vs 9.5 s for the same executable), and
    # 'best of 2' is the cheapest stable throughput statistic
    runs = 3 if warm else 1
    for _ in range(runs):
        t0 = time.perf_counter()
        # trace=True: span-level solve reports at negligible cost (a few
        # dozen perf_counter spans per solve) — the per-phase seconds
        # below localize any regression in the BENCH trajectory to a
        # pipeline phase (docs/OBSERVABILITY.md)
        res = optimize(solver="tpu", seed=seed, trace=True, **knobs,
                       **sc.kwargs)
        walls.append(time.perf_counter() - t0)
    cache1 = bucket.STATS.snapshot()
    # per-phase seconds of the LAST run (the best-warm representative):
    # bounds/constructor/seed/ladder/polish/verify from the solve report
    trace_rep = res.solve.stats.get("solve_report") or {}
    phase_s = {
        k: round(v, 4)
        for k, v in (trace_rep.get("phases") or {}).items()
        if k in PHASE_ORDER
    }
    # constructor host time (ISSUE 10): the summed sub-phase seconds
    # the solve report rolls up (obs.trace.SUB_PHASES) — flow bounds +
    # greedy + exact reseat + plan adoption, wherever they ran (race
    # workers included)
    construct_host_s = round(sum(
        v for k, v in (trace_rep.get("phases") or {}).items()
        if k in SUB_PHASES
    ), 4)

    # same-bucket reuse probe (warm search rows only): a DIFFERENT
    # cluster — a few partitions dropped, same bucket — must reuse the
    # executables the runs above compiled; `compiles: 0` here is the
    # shape-bucketing acceptance signal in the bench artifact
    bucket_reuse = None
    n_parts_full = len(sc.current.partitions)
    if (
        warm and knobs and n_parts_full > 8
        and bucket.part_bucket(n_parts_full - 3)
        == bucket.part_bucket(n_parts_full)
    ):
        from kafka_assignment_optimizer_tpu.models.cluster import Assignment

        variant_kwargs = dict(sc.kwargs)
        variant_kwargs["current"] = Assignment(
            partitions=sc.current.partitions[:-3]
        )
        c0 = bucket.STATS.snapshot()
        t0 = time.perf_counter()
        res_v = optimize(solver="tpu", seed=seed + 1, **knobs,
                         **variant_kwargs)
        wall_v = time.perf_counter() - t0
        c1 = bucket.STATS.snapshot()
        bucket_reuse = {
            "partitions": n_parts_full - 3,
            "bucket_parts": res_v.solve.stats.get("bucket_parts"),
            # which path the variant actually ran: "sweep"/"chain" mean
            # genuine executable reuse on the device; "construct" means
            # a host-side certificate beat the device to it (compiles
            # is then trivially 0 — still no compile in the wall clock)
            "engine": res_v.solve.stats.get("engine"),
            "wall_s": round(wall_v, 3),
            "compiles": c1["compiles_total"] - c0["compiles_total"],
            "compile_s": round(
                c1["compile_seconds_total"] - c0["compile_seconds_total"],
                3,
            ),
            "cache_hit": c1["compiles_total"] == c0["compiles_total"],
            "feasible": res_v.report()["feasible"],
        }
    # pipeline A/B (adversarial search rows, warm only): the same solve
    # with the double-buffered ladder dispatch disabled — identical
    # executables (pipelining is host orchestration, so the cache stays
    # warm), best of 2 against the pipelined best-warm. >= 1.0 means
    # the overlap is paying for itself in wall-clock; the per-chunk
    # overlap evidence lives in the solve report's boundary_overlap_s
    # span fields either way (docs/PIPELINE.md).
    pipeline_speedup = None
    if warm and knobs:
        nopipe = []
        for _ in range(2):
            t0 = time.perf_counter()
            # trace=True matches the pipelined baseline runs above —
            # the A/B must isolate the dispatcher, not tracing overhead
            optimize(solver="tpu", seed=seed, trace=True, pipeline=False,
                     **knobs, **sc.kwargs)
            nopipe.append(time.perf_counter() - t0)
        if min(walls[1:]) > 0:
            pipeline_speedup = round(min(nopipe) / min(walls[1:]), 3)
    # megachunk A/B (ISSUE 17, docs/PIPELINE.md): the same warm search
    # solve with K=8 chunks fused per dispatch. The fused scan is a
    # DIFFERENT executable, so run 0 pays its compile and best-of-rest
    # is the measured arm. Two verdicts ride the artifact: the wall
    # ratio (chunked best-warm / fused best-warm, >= 1.0 means fusion
    # pays) and the deterministic parity gate — the fused plan must be
    # bit-identical to the chunked plan whenever both walked the same
    # rounds (a deadline-shortened ladder is noise, not a regression).
    megachunk_speedup = megachunk_ab = None
    if warm and knobs:
        import numpy as np

        mwalls, mres = [], None
        for _ in range(3):
            t0 = time.perf_counter()
            mres = optimize(solver="tpu", seed=seed, trace=True,
                            megachunk=8, **knobs, **sc.kwargs)
            mwalls.append(time.perf_counter() - t0)
        st, mst = res.solve.stats, mres.solve.stats
        parity_ok = None
        if st.get("rounds_run") == mst.get("rounds_run"):
            parity_ok = bool(np.array_equal(mres.solve.a, res.solve.a))
        if min(walls[1:]) > 0 and min(mwalls[1:]) > 0:
            megachunk_speedup = round(
                min(walls[1:]) / min(mwalls[1:]), 3)
        dchunked, dmega = st.get("dispatches"), mst.get("dispatches")
        megachunk_ab = {
            "k": (mst.get("megachunk") or {}).get("k"),
            "wall_chunked_s": round(min(walls[1:]), 3),
            "wall_mega_s": round(min(mwalls[1:]), 3),
            "dispatches_chunked": dchunked,
            "dispatches_mega": dmega,
            # the headline dispatch-amplification claim: >= 4.0 at K=8
            # on a warm >= 8-chunk ladder (fewer chunks cap it)
            "dispatch_reduction": (
                round(dchunked / dmega, 2)
                if dchunked and dmega else None
            ),
            "duty_cycle_mega": _duty_cycle(mst),
            "feasible_mega": mres.report()["feasible"],
            "parity_ok": parity_ok,
        }
    default_wall = default_proved = None
    if knobs:
        t0 = time.perf_counter()
        res_d = optimize(solver="tpu", seed=seed, **sc.kwargs)
        default_wall = round(time.perf_counter() - t0, 3)
        default_proved = res_d.report()["proven_optimal"]
    report = res.report()
    cold, warm_wall = walls[0], min(walls[1:]) if warm else walls[0]
    return {
        "scenario": sc.name,
        # end-to-end optimize() time: parse -> model -> solve -> decode -> diff
        "wall_clock_s": round(warm_wall, 3),
        "cold_wall_clock_s": round(cold, 3),
        # every in-process run (run 0 = cold), so the artifact carries
        # the warm SPREAD, not one draw (VERDICT r4 item 3)
        "wall_clock_runs": [round(w, 3) for w in walls],
        # compile + first-trace overhead: cold minus warm (only meaningful
        # when both runs executed)
        "compile_s": round(cold - warm_wall, 3) if warm else None,
        "solver_s": report["solver_wall_clock_s"],
        "warm": warm,
        "platform": jax.devices()[0].platform,
        "engine": report.get("solver_engine"),
        "scorer": report.get("solver_scorer"),
        "pallas_fallback": report.get("solver_pallas_fallback"),
        # executable-cache movement across this child's runs: compiles
        # happen on run 0; warm runs must be pure hits
        "cache": {
            "exec_hits": cache1["exec_hits"] - cache0["exec_hits"],
            "exec_misses": cache1["exec_misses"] - cache0["exec_misses"],
            "compiles": cache1["compiles_total"] - cache0["compiles_total"],
            "compile_seconds": round(
                cache1["compile_seconds_total"]
                - cache0["compile_seconds_total"], 3,
            ),
        },
        "bucket_parts": report.get("solver_bucket_parts"),
        "bucket_rf": report.get("solver_bucket_rf"),
        # per-phase wall seconds of the representative run (solve-trace
        # telemetry): localizes a wall-clock regression to bounds /
        # constructor / seed / ladder / polish / verify
        "phase_s": phase_s,
        # summed constructor sub-phase host seconds (bounds_flow +
        # greedy + reseat + adopt — docs/CONSTRUCTOR.md)
        "construct_host_s": construct_host_s,
        # pipeline-on/off A/B on the warm search rows (null elsewhere)
        "pipeline_speedup": pipeline_speedup,
        "pipeline": res.solve.stats.get("pipeline"),
        # ladder dispatch accounting (ISSUE 17): host->device round
        # trips this solve paid, and the device share of the busy wall
        # (device_s / (device_s + dispatch_s)) — the number megachunk
        # fusion exists to raise
        "dispatches_per_solve": res.solve.stats.get("dispatches"),
        "duty_cycle": _duty_cycle(res.solve.stats),
        # chunked-vs-fused A/B on the warm search rows (null elsewhere)
        "megachunk_speedup": megachunk_speedup,
        **({"megachunk_ab": megachunk_ab} if megachunk_ab else {}),
        **({"bucket_reuse": bucket_reuse} if bucket_reuse else {}),
        "moves": report["replica_moves"],
        "min_moves_lb": sc.min_moves_lb,
        "lb_tight": sc.lb_tight,
        "leader_changes": report["leader_changes"],
        "feasible": report["feasible"],
        # True when the engine certified the plan against its LP/flow
        # bounds: provably weight-optimal AND move-optimal
        "proved_optimal": report["proven_optimal"],
        # constructor evidence: whether the plan was BUILT (aggregated
        # MILP / exact LP vertex) rather than annealed
        "constructed": report.get("solver_constructed"),
        "construct_path": report.get("solver_construct_path"),
        "objective": report["objective_weight"],
        "objective_ub": report["objective_upper_bound"],
        "brokers": report["brokers"],
        "partitions": report["partitions"],
        # adversarial rows only: the knob-free path (greedy+reseat
        # race, no device) on the same instance — the number a default
        # caller actually sees
        **(
            {"default_wall_clock_s": default_wall,
             "default_proved_optimal": default_proved}
            if default_wall is not None else {}
        ),
        **_sampler_block(sampler),
        **_profile_block(),
    }


def _profile_block() -> dict:
    """The headline row's roofline/attribution columns (obs.prof,
    docs/OBSERVABILITY.md "Reading a roofline"): the dominant
    executable's cost model + achieved occupancy, and the last solve's
    ledger shares — the measured columns regress.py's efficiency gate
    compares between artifacts (occupancy RATIO drops and attribution
    share shifts trip exit 3 like any latency regression)."""
    from kafka_assignment_optimizer_tpu.obs import flight as _flight
    from kafka_assignment_optimizer_tpu.obs import prof as _prof

    prof: dict = {}
    try:
        rows = _prof.snapshot()["executables"]
        if rows:
            top = rows[0]  # most device seconds = the dominant exec
            for f in ("flops", "bytes_accessed", "peak_hbm_bytes",
                      "occupancy_flops", "occupancy_hbm",
                      "occupancy_hbm_p50", "occupancy_hbm_p99",
                      "dispatches", "device_s"):
                if top.get(f) is not None:
                    prof[f] = top[f]
        led = None
        for rec in reversed(_flight.recent(8)):
            if isinstance(rec.get("ledger"), dict):
                led = rec["ledger"]
                break
        if led:
            wall = float(led.get("wall_s") or 0.0)
            if wall > 0:
                prof["device_share"] = round(
                    float(led.get("device_s") or 0.0) / wall, 4)
                prof["ledger_shares"] = {
                    f: round(float(led.get(f) or 0.0) / wall, 4)
                    for f in _prof.LEDGER_FIELDS
                }
            prof["ledger_ok"] = bool(led.get("ok"))
    except Exception:
        pass
    return {"profile": prof} if prof else {}


def _duty_cycle(stats: dict) -> float | None:
    """Device share of the ladder's busy wall, from the solve stats'
    measured split — the same device_s/(device_s + dispatch_s) the
    flight recorder stamps (obs/flight.py), so artifact and flight
    views can never disagree."""
    device_s = float(stats.get("device_s") or 0.0)
    dispatch_s = float(stats.get("dispatch_s") or 0.0)
    busy = device_s + dispatch_s
    return round(device_s / busy, 4) if busy > 0 else None


def _sampler_block(sampler) -> dict:
    """The headline row's ``device_sampler`` block (when armed):
    duty cycle, per-device memory, and the sampler's self-measured
    overhead fraction — the continuously observed form of the
    roofline-headroom claim."""
    if sampler is None:
        return {}
    snap = sampler.snapshot()
    sampler.stop()
    return {"device_sampler": {
        "hz": snap["hz"],
        "samples_total": snap["samples_total"],
        "overhead_frac": snap["overhead_frac"],
        "avg_sample_s": snap["avg_sample_s"],
        "duty_cycle": snap["duty_cycle"],
        "devices": snap["devices"],
    }}


def run_batch_throughput(smoke: bool, seed: int) -> dict:
    """Batched multi-instance lane throughput (the PR-2 tentpole
    evidence): B ∈ {1, 2, 4, 8} same-bucket adversarial instances
    through ``engine.solve_tpu_batch``, reporting solves/s per width,
    the B=8-vs-sequential speedup, and per-lane quality parity — every
    lane must be feasible with moves at its instance's exact certificate
    bound (adversarial decommissions have a tight lb: the replicas
    hosted by the removed broker). Each width warms its executable
    first, so the timed numbers are the steady-state throughput a
    coalescing service actually sees."""
    from kafka_assignment_optimizer_tpu.utils.platform import pin_platform

    pin_platform()
    import jax

    from kafka_assignment_optimizer_tpu.models.instance import build_instance
    from kafka_assignment_optimizer_tpu.solvers.tpu.engine import (
        solve_tpu_batch,
    )
    from kafka_assignment_optimizer_tpu.utils import gen

    kw = dict(gen.SMOKE_KWARGS["adversarial"]) if smoke else {}
    lanes = 8
    insts = []
    for i in range(lanes):
        # distinct generator seeds: 8 DIFFERENT clusters of one bucket
        sc = gen.adversarial(seed=7 + i, **kw)
        insts.append(
            build_instance(sc.current, sc.broker_list, sc.topology)
        )
    bounds = [int(inst.move_lower_bound_exact()) for inst in insts]
    knobs = dict(engine="sweep")
    if smoke:
        knobs["rounds"] = 16  # CPU smoke: keep the 15 solves in seconds

    # sequential baseline: all 8 instances one at a time through the
    # SAME lane path at B=1 (identical code, batching the only delta)
    solve_tpu_batch(insts[:1], seeds=seed, **knobs)  # warm B=1
    t0 = time.perf_counter()
    seq = []
    for i, inst in enumerate(insts):
        seq.extend(solve_tpu_batch([inst], seeds=seed + i, **knobs))
    wall_seq = time.perf_counter() - t0
    widths: dict[str, dict] = {
        "b1": {
            "solves_per_s": round(lanes / wall_seq, 4),
            "wall_s": round(wall_seq, 3),
            "feasible": sum(r.stats["feasible"] for r in seq),
        }
    }
    batched = {}
    for B in (2, 4, 8):
        sub, sub_seeds = insts[:B], [seed + i for i in range(B)]
        solve_tpu_batch(sub, seeds=sub_seeds, **knobs)  # warm this width
        t0 = time.perf_counter()
        res = solve_tpu_batch(sub, seeds=sub_seeds, **knobs)
        wall = time.perf_counter() - t0
        widths[f"b{B}"] = {
            "solves_per_s": round(B / wall, 4),
            "wall_s": round(wall, 3),
            "feasible": sum(r.stats["feasible"] for r in res),
        }
        batched[B] = res
    res8 = batched[8]
    lanes_feasible = all(r.stats["feasible"] for r in res8)
    moves_ok = all(
        r.stats["moves"] <= bounds[i] for i, r in enumerate(res8)
    )
    # per-solve quality parity: batched lane i vs its sequential solve
    parity = [
        {
            "lane": i,
            "moves": r.stats["moves"],
            "seq_moves": seq[i].stats["moves"],
            "objective": r.objective,
            "seq_objective": seq[i].objective,
            "bound": bounds[i],
        }
        for i, r in enumerate(res8)
    ]
    speedup = round(
        widths["b8"]["solves_per_s"] / widths["b1"]["solves_per_s"], 3
    ) if widths["b1"]["solves_per_s"] > 0 else 0.0
    return {
        "platform": jax.devices()[0].platform,
        "lanes": lanes,
        "brokers": insts[0].num_brokers,
        "partitions": insts[0].num_parts,
        "widths": widths,
        "speedup_b8_vs_seq": speedup,
        "lanes_feasible": lanes_feasible,
        "moves_at_bound": moves_ok,
        "parity": parity,
    }


def run_portfolio_ab(smoke: bool, seed: int) -> dict:
    """Portfolio A/B (the PR-11 tentpole evidence, docs/PORTFOLIO.md):
    the messy worst-case family (``gen.messy_case`` — irregular
    topics/RFs, lopsided racks, exact bands; seed 1 is the instance
    that was the tier-1 xfail) solved twice per case at EQUAL search
    budget — portfolio OFF (one default config) vs portfolio ON (the
    diverse lane table racing through the one lane-padded executable
    per bucket). Scored on the deterministic signals: per-arm feasible
    and certify counts, the worst case's violation count, summed
    objective over feasible cases, and time-to-first-certificate for
    early-exited solves. The exec-cache compile counters across the
    portfolio arm pin the consolidation claim: every width shares the
    bucket's one lane executable."""
    from kafka_assignment_optimizer_tpu.utils.platform import pin_platform

    pin_platform()
    import jax

    from kafka_assignment_optimizer_tpu.api import optimize
    from kafka_assignment_optimizer_tpu.solvers.tpu import bucket
    from kafka_assignment_optimizer_tpu.utils import gen

    cases = list(range(4 if smoke else 8))
    # 16 sweeps is the discriminating budget: the single default config
    # leaves the exact-band case (seed 1) infeasible while the
    # portfolio's diverse lanes close it — at 32+ even the solo path
    # eventually stumbles through on some hosts, washing out the A/B
    rounds = 16
    knobs = dict(engine="sweep", batch=8, rounds=rounds)

    def arm(portfolio: bool) -> dict:
        feasible = certified = early = 0
        worst_viol = 0
        obj_total = 0
        walls, ttfc = [], []
        for cs in cases:
            current, brokers, topo, trf = gen.messy_case(cs)
            t0 = time.perf_counter()
            res = optimize(current, brokers, topo, target_rf=trf,
                           solver="tpu", seed=seed + cs,
                           portfolio=portfolio, **knobs)
            walls.append(time.perf_counter() - t0)
            rep = res.report()
            viol = sum(rep["violations"].values())
            worst_viol = max(worst_viol, viol)
            if rep["feasible"]:
                feasible += 1
                obj_total += rep["objective_weight"]
            if rep["proven_optimal"]:
                certified += 1
            port = res.solve.stats.get("portfolio") or {}
            if port.get("early_exit"):
                early += 1
                if port.get("certified_at_s") is not None:
                    ttfc.append(float(port["certified_at_s"]))
        return {
            "feasible": feasible,
            "certified": certified,
            "early_exit": early,
            "worst_violations": worst_viol,
            "objective_total": obj_total,
            "wall_s_total": round(sum(walls), 3),
            "wall_p50_s": _pctile(walls, 50),
            "ttfc_p50_s": _pctile(ttfc, 50),
        }

    # warm EVERY case's executables in both arms before timing: the
    # messy family varies broker/rack counts per seed, and those axes
    # are exact in the bucket key (docs/BUCKETING.md) — warming only
    # one case would leave the other timed rows paying XLA compiles,
    # turning the latency columns into compile jitter and the
    # compiles-per-arm consolidation evidence into noise
    for cs in cases:
        wc, wb, wt, wr = gen.messy_case(cs)
        for port in (False, True):
            optimize(wc, wb, wt, target_rf=wr, solver="tpu", seed=seed,
                     portfolio=port, **knobs)
    c0 = bucket.STATS.snapshot()
    single = arm(False)
    c1 = bucket.STATS.snapshot()
    port = arm(True)
    c2 = bucket.STATS.snapshot()
    return {
        "platform": jax.devices()[0].platform,
        "cases": len(cases),
        "rounds": rounds,
        "single": single,
        "portfolio": port,
        # the PR's quality claim, as one deterministic bit: at equal
        # budget the portfolio's worst case is no worse and it closes
        # at least as many cases
        "quality_win": (
            port["worst_violations"] <= single["worst_violations"]
            and port["feasible"] >= single["feasible"]
        ),
        # consolidation evidence: the portfolio arm's timed cases run
        # on the executables the warmup row compiled — zero compiles
        "compiles_single_arm": (
            c1["compiles_total"] - c0["compiles_total"]
        ),
        "compiles_portfolio_arm": (
            c2["compiles_total"] - c1["compiles_total"]
        ),
    }


def _pctile(xs: list, q: float) -> float | None:
    """Nearest-rank percentile of a small latency sample."""
    if not xs:
        return None
    xs = sorted(xs)
    k = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return round(xs[k], 4)


def _replay_day_script(smoke: bool) -> tuple:
    """The scripted event day (docs/WATCH.md): a rolling two-broker
    decommission, partition growth, a rack loss + recovery, then an
    event storm. Returns ``(cluster_id, bootstrap_event,
    sequential_events, storm_events)`` — epochs pre-assigned, storm
    epochs contiguous after the sequence."""
    from kafka_assignment_optimizer_tpu.utils import gen

    B = 12 if smoke else 64
    n_racks = 4
    ppt = 10 if smoke else 40
    topics = {f"t{i}": ppt for i in range(4 if smoke else 20)}
    rf = 3
    brokers = list(range(B))
    topo = gen._mod_topology(brokers, n_racks)
    current = gen.balanced_assignment(brokers, topo, topics, rf)
    fail_rack = topo.rack(1)
    failed = [b for b in brokers if topo.rack(b) == fail_rack
              and b not in (B - 1, B - 2)]
    bootstrap = {
        "type": "bootstrap", "epoch": 1,
        "assignment": current.to_dict(), "brokers": brokers,
        "topology": topo.to_dict(), "rf": rf,
    }
    seq = [
        # a rolling decommission: drain, drain, forget
        {"type": "broker_drain", "epoch": 2, "brokers": [B - 1]},
        {"type": "broker_drain", "epoch": 3, "brokers": [B - 2]},
        {"type": "broker_remove", "epoch": 4, "brokers": [B - 1, B - 2]},
        # a topic grows mid-day
        {"type": "partition_growth", "epoch": 5, "topic": "t0",
         "add": ppt // 2},
        # a rack fails...
        {"type": "rack_fail", "epoch": 6, "rack": fail_rack},
        # ...and comes back
        {"type": "broker_add", "epoch": 7, "brokers": failed,
         "racks": {str(b): fail_rack for b in failed}},
    ]
    # the storm: a controller rapid-fires flap events while the first
    # one's solve is still in flight — the registry must coalesce them
    # into ONE re-solve of the latest state and drop none
    storm = []
    e = 8
    for _ in range(5):
        storm.append({"type": "broker_drain", "epoch": e, "brokers": [0]})
        storm.append({"type": "broker_add", "epoch": e + 1, "brokers": [0]})
        e += 2
    return "replay-day", bootstrap, seq, storm


def run_replay_day(smoke: bool, seed: int) -> dict:
    """The event-day replay harness (ISSUE 7 tentpole evidence): ONE
    scripted day of cluster events through the watch state machine on
    the warm product path (each delta solve seeded by the previous
    plan via ``optimize_delta``), with a PAIRED shadow cold solve of
    the IDENTICAL cluster state at every sequential event. Pairing is
    what makes the per-event comparison meaningful: a two-arm design
    (one warm stream, one cold stream) lets the arms' incumbent states
    diverge at the first uncertified event — each arm's next event
    diffs against its OWN previous plan — after which per-event move
    counts and objectives compare annealer luck on different
    instances, not warm-starting. The storm segment runs on the warm
    stream only (its gate is coalescing with zero drops, not plan
    quality). Reports per-event end-to-end latency (p50/p99, paired),
    plan quality, and move counts."""
    from kafka_assignment_optimizer_tpu.utils.platform import pin_platform

    pin_platform()
    import threading
    from dataclasses import replace as _dc_replace

    import jax

    from kafka_assignment_optimizer_tpu.api import optimize_delta
    from kafka_assignment_optimizer_tpu.models.cluster import Assignment
    from kafka_assignment_optimizer_tpu.watch.events import apply_event
    from kafka_assignment_optimizer_tpu.watch.manager import WatchRegistry

    cid, bootstrap, seq, storm = _replay_day_script(smoke)
    limit_s = 60.0 if smoke else 300.0

    def solve_once(state, prev_plan, budget=None):
        return optimize_delta(
            state.assignment, state.brokers, state.topology,
            target_rf=state.rf, prev_plan=prev_plan,
            solver="tpu", seed=seed, budget=budget,
            time_limit_s=limit_s,
        )

    def row_of(ev: dict, rep: dict, dt: float) -> dict:
        return {
            "type": ev["type"], "epoch": ev["epoch"],
            "wall_s": round(dt, 4),
            "moves": rep.get("replica_moves"),
            "feasible": rep.get("feasible"),
            "proved": rep.get("proven_optimal"),
            "warm_started": bool(rep.get("solver_warm_started")),
            "objective": rep.get("objective_weight"),
            "objective_ub": rep.get("objective_upper_bound"),
        }

    # unmeasured warmup pass: both measured columns must see warmed
    # jit/executable caches — without it the first solves pay every
    # compile and the comparison measures XLA, not warm-starting
    mirror = None
    for ev in [bootstrap] + seq:
        mirror = apply_event(mirror, cid, ev)
        res = solve_once(mirror, None)
        mirror = _dc_replace(mirror, assignment=res.assignment)

    storm_hold: dict = {"gate": None}

    def solve_fn(state, prev_plan, budget):
        res = solve_once(state, prev_plan, budget)
        gate = storm_hold["gate"]
        if gate is not None:
            # storm segment: the first in-flight solve is held open
            # until the whole burst has been fired, so the coalescing
            # evidence is deterministic — not a race between a sleep
            # and however fast this machine happens to solve
            gate.wait(timeout=30)
        return res.assignment.to_dict(), res.report()

    reg = WatchRegistry(solve_fn, None, window_s=0.05,
                        max_backlog=1024)
    warm_rows: list[dict] = []
    cold_rows: list[dict] = []
    warm_lat: list[float] = []
    cold_lat: list[float] = []
    mirror = None
    for ev in [bootstrap] + seq:
        # the state this event's solve will see, mirrored through the
        # same pure transition the registry applies
        mirror = apply_event(mirror, cid, ev)
        t0 = time.perf_counter()
        out = reg.handle_event(cid, ev)
        dt = time.perf_counter() - t0
        warm_lat.append(dt)
        warm_rows.append(row_of(ev, out.get("report") or {}, dt))
        # paired shadow: the SAME cluster state, solved from scratch
        # (outside the stream, so it never pollutes the warm latency)
        t0 = time.perf_counter()
        cres = solve_once(mirror, None)
        cdt = time.perf_counter() - t0
        cold_lat.append(cdt)
        cold_rows.append(row_of(ev, cres.report(), cdt))
        # the stream carries the warm plan forward, and so must the
        # mirror the next event's transition starts from
        mirror = _dc_replace(mirror, assignment=Assignment.from_dict(
            out["assignment"]))
    # storm segment: thread A's event takes the solver role; the
    # rapid-fire rest must coalesce behind it (202-equivalent acks).
    # The gate holds A's solve open until the burst has been fired.
    first, rest = storm[0], storm[1:]
    storm_hold["gate"] = threading.Event()
    t_storm = time.perf_counter()
    a = threading.Thread(target=reg.handle_event, args=(cid, first))
    a.start()
    # fire the burst only once A actually HOLDS the solver role —
    # otherwise the first burst event would take it on this thread and
    # wait on a gate only this thread can set
    role_deadline = time.perf_counter() + 10.0
    while time.perf_counter() < role_deadline:
        if (reg.get_cluster(cid) or {}).get("solving"):
            break
        time.sleep(0.001)
    acks = 0
    ack_lat: list[float] = []
    for ev in rest:
        t0 = time.perf_counter()
        out = reg.handle_event(cid, ev)
        ack_lat.append(time.perf_counter() - t0)
        acks += int(out.get("status") == "accepted")
    storm_hold["gate"].set()
    storm_hold["gate"] = None  # the drain re-solve runs unheld
    a.join()
    deadline = time.perf_counter() + limit_s * 4
    while time.perf_counter() < deadline:
        info = reg.get_cluster(cid)
        if not info["solving"] and info["pending_events"] == 0:
            break
        time.sleep(0.05)
    storm_s = time.perf_counter() - t_storm
    info = reg.get_cluster(cid)
    snap = reg.snapshot()
    last_epoch = storm[-1]["epoch"]

    def arm(rows: list[dict], lat: list[float]) -> dict:
        solves = [r for r in rows if r["moves"] is not None]
        # percentiles over the DELTA events only: the bootstrap solve
        # is identical in both columns by construction (no previous
        # plan to warm from), so including it just parks noise at the
        # median of a 7-sample set
        delta_lat = lat[1:]
        return {
            "p50_s": _pctile(delta_lat, 50),
            "p99_s": _pctile(delta_lat, 99),
            "latencies_s": [round(x, 4) for x in lat],
            "rows": rows,
            "certified_events": sum(1 for r in solves if r["proved"]),
            "all_feasible": all(r["feasible"] for r in solves),
            "moves_total": sum(r["moves"] for r in solves),
        }

    warm = arm(warm_rows, warm_lat)
    cold = arm(cold_rows, cold_lat)
    warm["warm_solves"] = snap["warm_solves_total"]
    warm["storm"] = {
        "acks_coalesced": acks,
        "ack_latencies_s": [round(x, 4) for x in ack_lat],
        "sheds": snap["storm_sheds_total"],
        "superseded": snap["superseded_total"],
        "drain_s": round(storm_s, 3),
        "final_epoch": info["epoch"],
        "final_plan_epoch": info["plan_epoch"],
    }
    # quality gate, per paired event (identical instance on both
    # sides): feasible, certified whenever the shadow cold solve
    # certified, and an at-least-as-good objective; across the day,
    # the warm stream must not move more data in total
    quality_ok = all(
        w["feasible"] and (not c["proved"] or w["proved"])
        and (w["objective"] is None or c["objective"] is None
             or w["objective"] >= c["objective"])
        for w, c in zip(warm["rows"], cold["rows"])
    ) and warm["moves_total"] <= cold["moves_total"]
    dropped = (
        warm["storm"]["sheds"]
        + int(warm["storm"]["final_plan_epoch"] != last_epoch)
    )
    # flight-recorder evidence (docs/OBSERVABILITY.md): every event the
    # registry solved landed ONE kind="delta" record via the manager's
    # ambient tagging — the per-event cost ledger the SLO engine reads
    from kafka_assignment_optimizer_tpu.obs import flight as _flight

    delta_records = len(_flight.recent(kind="delta"))
    return {
        "platform": jax.devices()[0].platform,
        "events": len(seq) + 1 + len(storm),
        "flight_delta_records": delta_records,
        "warm": warm,
        "cold": cold,
        "latency_win": (
            warm["p50_s"] is not None and cold["p50_s"] is not None
            and warm["p50_s"] < cold["p50_s"]
        ),
        "quality_ok": quality_ok,
        "storm_dropped": dropped,
    }


def run_decompose_bench(smoke: bool, seed: int) -> dict:
    """``--decompose-bench`` (docs/DECOMPOSE.md, ISSUE 16): the
    decomposed map-reduce rung's evidence. One ultra-jumbo
    AZ-structured decommission solved COLD through the decomposed path
    (``ultra_jumbo_cold_s``), with the stitched plan re-verified here
    against the flat instance's oracle (``stitched_feasible``) and the
    certificate-or-bound-gap contract checked (``gap_ok``); plus a
    decomposed-vs-flat A/B on the largest instance the flat path still
    survives (``decompose_speedup``). Sub-problem count, iterations
    and bound gap are stamped for obs/regress.py."""
    from kafka_assignment_optimizer_tpu.utils.platform import pin_platform

    pin_platform()
    from kafka_assignment_optimizer_tpu.models.instance import (
        build_instance,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu.engine import solve_tpu
    from kafka_assignment_optimizer_tpu.utils import gen

    limit_s = 120.0 if smoke else 900.0
    big_sc = (
        gen.ultra_jumbo(seed=seed, **gen.SMOKE_KWARGS["ultra_jumbo"])
        if smoke else gen.ultra_jumbo_case(seed)
    )
    inst_big = build_instance(**big_sc.kwargs)
    t0 = time.perf_counter()
    res_big = solve_tpu(inst_big, seed=seed, decompose=True,
                        time_limit_s=limit_s)
    ultra_cold_s = time.perf_counter() - t0
    d = res_big.stats.get("decompose") or {}
    viol = int(sum(inst_big.violations(res_big.a).values()))
    stitched_feasible = bool(
        res_big.stats.get("engine") == "decomposed" and viol == 0
    )
    obj = int(res_big.objective or 0)
    gap = int(d.get("bound_gap") or 0)
    # the contract: a certificate, or a reported gap within 15% of the
    # achieved objective (level-0 upper bounds are deliberately loose)
    gap_ok = bool(d.get("certified")) or (
        stitched_feasible and obj > 0 and gap <= 0.15 * obj
    )

    # flat-vs-decomposed A/B: smoke reuses the instance above (the
    # decomposed wall already measured); full mode compares on a
    # 50k-partition ultra-jumbo — jumbo scale, which flat survives
    if smoke:
        cmp_sc, dec_s, r_d = big_sc, ultra_cold_s, res_big
        inst_cmp = inst_big
    else:
        cmp_sc = gen.ultra_jumbo(seed=seed, partitions=50_000)
        inst_cmp = build_instance(**cmp_sc.kwargs)
        t0 = time.perf_counter()
        r_d = solve_tpu(inst_cmp, seed=seed, decompose=True,
                        time_limit_s=limit_s)
        dec_s = time.perf_counter() - t0
    inst_flat = build_instance(**cmp_sc.kwargs)
    t0 = time.perf_counter()
    r_f = solve_tpu(inst_flat, seed=seed, decompose=False,
                    time_limit_s=limit_s)
    flat_s = time.perf_counter() - t0

    return {
        "ultra_parts": int(inst_big.num_parts),
        "ultra_jumbo_cold_s": round(ultra_cold_s, 3),
        "sub_problems": int(d.get("subproblems") or 0),
        "iterations": int(d.get("iterations") or 0),
        "boundary_parts": int(d.get("boundary_parts") or 0),
        "bound_gap": gap,
        "certified": bool(d.get("certified")),
        "stitched_feasible": stitched_feasible,
        "gap_ok": gap_ok,
        "cmp_parts": int(inst_flat.num_parts),
        "decomposed_wall_s": round(dec_s, 3),
        "flat_wall_s": round(flat_s, 3),
        "decompose_speedup": (
            round(flat_s / dec_s, 3) if dec_s > 0 else 0.0
        ),
        "flat_feasible": bool(r_f.stats.get("feasible")),
        "decomposed_feasible": bool(r_d.stats.get("feasible")),
    }


def run_rollout_bench(smoke: bool, seed: int) -> dict:
    """``--rollout-bench`` (docs/ROLLOUT.md, ISSUE 12): one full
    supervised rollout through the watch registry + rollout manager on
    the real delta-solve path. Reports waves-to-completion under tight
    caps, the per-wave peak broker/rack transfer vs the caps —
    recomputed independently off the move graph, not read back from
    the packer's own accounting — and the re-plan latency after a
    mid-rollout broker loss (the remaining waves re-packed against the
    partially-moved ground truth)."""
    from kafka_assignment_optimizer_tpu.utils.platform import pin_platform

    pin_platform()
    import jax

    from kafka_assignment_optimizer_tpu.api import optimize_delta
    from kafka_assignment_optimizer_tpu.rollout.exec import RolloutManager
    from kafka_assignment_optimizer_tpu.utils import gen
    from kafka_assignment_optimizer_tpu.watch.manager import WatchRegistry

    B = 12 if smoke else 48
    n_racks = 4
    ppt = 10 if smoke else 40
    topics = {f"t{i}": ppt for i in range(4 if smoke else 12)}
    rf = 3
    brokers = list(range(B))
    topo = gen._mod_topology(brokers, n_racks)
    current = gen.balanced_assignment(brokers, topo, topics, rf)
    limit_s = 60.0 if smoke else 300.0

    def solve_fn(state, prev_plan, budget):
        res = optimize_delta(
            state.assignment, state.brokers, state.topology,
            target_rf=state.rf, prev_plan=prev_plan, solver="auto",
            seed=seed, time_limit_s=limit_s,
        )
        return res.assignment.to_dict(), res.report()

    reg = WatchRegistry(solve_fn, None, window_s=0.0)
    broker_cap, rack_cap = (3, 8) if smoke else (6, 16)
    mgr = RolloutManager(reg, None, broker_cap=broker_cap,
                         rack_cap=rack_cap)
    cid = "rollout-bench"
    reg.handle_event(cid, {
        "type": "bootstrap", "epoch": 1,
        "assignment": current.to_dict(), "brokers": brokers,
        "topology": topo.to_dict(), "rf": rf,
    })
    # the day's work: decommission two brokers -> a plan with real moves
    reg.handle_event(cid, {"type": "broker_drain", "epoch": 2,
                           "brokers": [B - 1, B - 2]})

    t0 = time.perf_counter()
    view = mgr.command(cid, "start", {"epoch": 1})
    pack_s = time.perf_counter() - t0
    waves_planned = view["waves"]

    def wave_caps_ok() -> tuple[bool, int, int]:
        """Recompute every wave's peak loads from its own move graph
        (adds + sources against the live topology) and check the caps
        the record claims."""
        v = mgr.get(cid)
        t = reg.topology_of(cid)
        rack = (t.rack if t is not None else (lambda b: "r0"))
        rec = mgr._records[cid]
        peak_b = peak_r = 0
        ok = True
        for w in rec.plan.waves:
            bl, rl = {}, {}
            for m in w.moves:
                for b in m.adds:
                    bl[b] = bl.get(b, 0) + 1
                    r = rack(b)
                    rl[r] = rl.get(r, 0) + 1
                    if m.source is not None:
                        bl[m.source] = bl.get(m.source, 0) + 1
            wb = max(bl.values(), default=0)
            wr = max(rl.values(), default=0)
            peak_b, peak_r = max(peak_b, wb), max(peak_r, wr)
            ok = ok and wb <= v["caps"]["broker"] \
                and wr <= v["caps"]["rack"]
        return ok, peak_b, peak_r

    ep = 2
    view = mgr.command(cid, "advance", {"epoch": ep})            # canary
    ep += 1
    view = mgr.command(cid, "advance", {"epoch": ep,
                                        "canary_ok": True})
    ep += 1
    # mid-rollout broker loss: the watch channel re-solves against the
    # partially-moved truth and the rollout re-packs the REMAINING
    # waves — this wall clock IS the re-plan latency
    t1 = time.perf_counter()
    reg.handle_event(cid, {"type": "broker_remove", "epoch": 3,
                           "brokers": [0]})
    replan_s = time.perf_counter() - t1
    caps_ok, peak_b, peak_r = wave_caps_ok()
    view = mgr.get(cid)
    while view["status"] in ("canary", "advancing"):
        p = {"epoch": ep}
        if view["status"] == "canary":
            p["canary_ok"] = True
        view = mgr.command(cid, "advance", p)
        ep += 1
    total_s = time.perf_counter() - t0
    info = reg.get_cluster(cid)
    return {
        "platform": jax.devices()[0].platform,
        "brokers": B,
        "partitions": sum(topics.values()),
        "waves_planned": waves_planned,
        "waves_applied": len(view["applied"]),
        "replans": view["replans"],
        "broker_cap": view["caps"]["broker"],
        "rack_cap": view["caps"]["rack"],
        "peak_broker": peak_b,
        "peak_rack": peak_r,
        "caps_ok": caps_ok,
        "terminal": view["status"],
        "terminal_ok": (
            view["status"] == "done"
            and info["assignment"] == info["plan"]
        ),
        "pack_s": round(pack_s, 4),
        "replan_s": round(replan_s, 4),
        "total_s": round(total_s, 4),
    }


# --------------------------------------------------------------------------
# --fleet-bench: router + N real workers vs a single worker (docs/FLEET.md)
# --------------------------------------------------------------------------

_FLEET_SHAPES = (
    {"brokers": 12, "partitions": 64, "rf": 3, "racks": 4},
    {"brokers": 12, "partitions": 200, "rf": 3, "racks": 4},
)


def _fleet_payload(shape: dict, idx: int) -> dict:
    """One /submit payload in ``shape``'s bucket with REAL repair work:
    every third partition is piled onto brokers 0-2, violating the
    balance bands, so the solve has genuine moves to find (a clean
    round-robin cluster certifies host-side in ~0 work and would
    measure only HTTP overhead)."""
    B, rf = shape["brokers"], shape["rf"]
    parts = []
    for i in range(shape["partitions"]):
        if i % 3 == 0:
            reps = [(i + j * 3) % 9 for j in range(rf)]
        else:
            reps = [(i + j) % B for j in range(rf)]
        parts.append({"topic": "fleet", "partition": i,
                      "replicas": reps})
    return {
        "assignment": {"version": 1, "partitions": parts},
        "brokers": list(range(B)),
        "topology": {str(b): f"rack{b % shape['racks']}"
                     for b in range(B)},
        "solver": "tpu",
        "options": {"seed": idx % 5},
    }


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http_json(url: str, payload=None, timeout: float = 300.0):
    """(status, body, headers) with 4xx/5xx bodies parsed, not raised."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url,
        data=(None if payload is None
              else json.dumps(payload).encode()),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}"), \
                dict(resp.headers)
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except ValueError:
            body = {}
        return e.code, body, dict(e.headers)


def _wait_up(port: int, deadline_s: float = 240.0) -> None:
    t0 = time.time()
    while True:
        try:
            _http_json(f"http://127.0.0.1:{port}/healthz", timeout=10)
            return
        except Exception as e:
            if time.time() - t0 > deadline_s:
                raise RuntimeError(
                    f"worker :{port} never came up: {e}") from e
            time.sleep(0.5)


def _fleet_load(base_url: str, requests: list[dict],
                clients: int) -> dict:
    """Drive ``requests`` closed-loop from ``clients`` threads against
    ``base_url``/submit, honoring Retry-After on 503 like a
    well-behaved external client. Returns wall, latency percentiles,
    per-request moves, and the shed/retry counts — completed MUST
    equal len(requests) (zero drops)."""
    import queue as _q
    import threading

    jobs: _q.Queue = _q.Queue()
    for i, payload in enumerate(requests):
        jobs.put((i, payload))
    lock = threading.Lock()
    out = {"lat": [], "moves": [], "feasible": 0, "completed": 0,
           "retries": 0, "errors": []}

    def worker():
        while True:
            try:
                i, payload = jobs.get_nowait()
            except _q.Empty:
                return
            t0 = time.perf_counter()
            deadline = time.time() + 300.0
            while True:
                try:
                    status, body, headers = _http_json(
                        f"{base_url}/submit", payload)
                except Exception as e:  # router/worker hiccup: retry
                    status, body, headers = 0, {"error": repr(e)}, {}
                if status == 200:
                    dt = time.perf_counter() - t0
                    rep = body.get("report") or {}
                    with lock:
                        out["lat"].append(dt)
                        out["completed"] += 1
                        out["moves"].append(
                            rep.get("replica_moves"))
                        out["feasible"] += bool(rep.get("feasible"))
                    break
                if status not in (0, 503):
                    # a 400/422/500 is a deterministic verdict, not
                    # saturation: retrying it would spin the full
                    # per-request deadline per request — fail fast
                    with lock:
                        out["errors"].append(
                            f"{status}: "
                            f"{str(body.get('error'))[:110]}")
                    break
                if time.time() > deadline:
                    with lock:
                        out["errors"].append(
                            str(body.get("error"))[:120])
                    break
                try:
                    wait = float(headers.get("Retry-After", 1))
                except (TypeError, ValueError):
                    wait = 1.0
                with lock:
                    out["retries"] += 1
                time.sleep(max(wait, 0.2))

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    out["wall_s"] = time.perf_counter() - t0
    return out


def run_fleet_bench(smoke: bool, seed: int, env: dict,
                    n_workers: int = 2) -> dict:
    """``--fleet-bench`` (docs/FLEET.md, ISSUE 14): spawn a kao-router
    + N REAL serve workers sharing one fresh ``KAO_COMPILE_CACHE``,
    fleet-warm the bucket ladder through the router (each bucket
    compiles exactly once fleet-wide; the spread phase must be all
    disk hits), then drive an identical mixed-bucket load through the
    fleet AND through a fresh single worker, reporting aggregate
    solves/s, p50/p99, the router's affinity hit rate, and the
    warmup's persistent-compile accounting."""
    import os
    import shutil
    import subprocess
    import tempfile

    M = 16 if smoke else 48
    clients = 4 if smoke else 6
    requests = [
        _fleet_payload(_FLEET_SHAPES[i % len(_FLEET_SHAPES)], i + seed)
        for i in range(M)
    ]
    shapes = list(_FLEET_SHAPES)
    work = tempfile.mkdtemp(prefix="kao-fleet-bench-")
    procs: list = []

    def spawn_worker(port: int, cache_dir: str):
        wenv = dict(env)
        wenv.update({
            "KAO_COMPILE_CACHE": cache_dir,
            "KAO_COMPILE_CACHE_MIN_S": "0",
        })
        p = subprocess.Popen(
            [sys.executable, "-m",
             "kafka_assignment_optimizer_tpu.serve",
             "--host", "127.0.0.1", "--port", str(port),
             "--workers", "1", "--queue-depth", "4",
             "--lock-wait-s", "5", "--max-solve-s", "120",
             # coalescing OFF for the measurement: batched lane
             # grouping is timing-sensitive (which requests land in
             # one dispatch changes the total work), and this harness
             # needs run-to-run comparability — the coalescing path
             # has its own dedicated bench (--batch-bench)
             "--max-batch", "1",
             "--no-trace"],
            env=wenv, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        procs.append(p)
        return p

    def stop_all():
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
        procs.clear()

    try:
        # -- arm 1: the single-worker baseline (own fresh cache) -----
        sport = _free_port()
        spawn_worker(sport, os.path.join(work, "jit-single"))
        _wait_up(sport)
        t0 = time.perf_counter()
        status, warm_single, _ = _http_json(
            f"http://127.0.0.1:{sport}/warmup", {"shapes": shapes},
            timeout=600,
        )
        single_warm_s = time.perf_counter() - t0
        if status != 200:
            raise RuntimeError(f"single warmup failed: {warm_single}")
        single = _fleet_load(f"http://127.0.0.1:{sport}", requests,
                             clients)
        stop_all()

        # -- arm 2: router + N workers, ONE shared cache -------------
        cache = os.path.join(work, "jit-fleet")
        wports = [_free_port() for _ in range(n_workers)]
        for p in wports:
            spawn_worker(p, cache)
        rport = _free_port()
        procs.append(subprocess.Popen(
            [sys.executable, "-m",
             "kafka_assignment_optimizer_tpu.fleet.router",
             "--host", "127.0.0.1", "--port", str(rport),
             "--workers", ",".join(f"http://127.0.0.1:{p}"
                                   for p in wports),
             "--health-interval-s", "0.5", "--lock-wait-s", "15"],
            env=dict(env), stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        ))
        for p in wports:
            _wait_up(p)
        _wait_up(rport)
        t0 = time.perf_counter()
        status, warm_fleet, _ = _http_json(
            f"http://127.0.0.1:{rport}/warmup", {"shapes": shapes},
            timeout=900,
        )
        fleet_warm_s = time.perf_counter() - t0
        if status != 200:
            raise RuntimeError(f"fleet warmup failed: {warm_fleet}")
        fleet = _fleet_load(f"http://127.0.0.1:{rport}", requests,
                            clients)
        _, rhz, _ = _http_json(f"http://127.0.0.1:{rport}/healthz",
                               timeout=30)
        stop_all()
    finally:
        stop_all()
        shutil.rmtree(work, ignore_errors=True)

    def pct(xs, q):
        return round(_pctile(sorted(xs), q), 4) if xs else None

    def thr(arm):
        return round(arm["completed"] / arm["wall_s"], 3) \
            if arm["wall_s"] > 0 else None

    affinity = (rhz.get("routing") or {}).get("affinity_rate")
    fleet_thr, single_thr = thr(fleet), thr(single)
    # equal quality: both arms solved the identical payloads with the
    # same seeds — every request feasible, and the same move totals
    quality_ok = (
        fleet["completed"] == M and single["completed"] == M
        and fleet["feasible"] == M and single["feasible"] == M
        and sorted(x for x in fleet["moves"] if x is not None)
        == sorted(x for x in single["moves"] if x is not None)
    )
    return {
        "workers": n_workers,
        "requests": M,
        "clients": clients,
        "host_cores": os.cpu_count(),
        # fleet arm (the headline --compare keys)
        "throughput": fleet_thr,
        "p50_s": pct(fleet["lat"], 50),
        "p99_s": pct(fleet["lat"], 99),
        "wall_s": round(fleet["wall_s"], 3),
        "retries": fleet["retries"],
        "dropped": M - fleet["completed"],
        # single-worker baseline
        "single_throughput": single_thr,
        "single_p50_s": pct(single["lat"], 50),
        "single_p99_s": pct(single["lat"], 99),
        "single_dropped": M - single["completed"],
        "speedup": (round(fleet_thr / single_thr, 3)
                    if fleet_thr and single_thr else None),
        # affinity + fleet-warmup accounting (docs/FLEET.md)
        "affinity_rate": affinity,
        "affinity_ok": (affinity is not None and affinity >= 0.9),
        "warmup_fresh_compiles": warm_fleet.get("fresh_compiles"),
        "warmup_spread_fresh_compiles":
            warm_fleet.get("spread_fresh_compiles"),
        # the acceptance proof: non-owner workers' warmup compiled
        # NOTHING fresh — every executable came off the shared disk
        # cache one owner populated
        "spread_ok": warm_fleet.get("spread_fresh_compiles") == 0,
        "fleet_warm_s": round(fleet_warm_s, 3),
        "single_warm_s": round(single_warm_s, 3),
        "quality_ok": quality_ok,
    }


def run_kernel_bench(smoke: bool) -> dict:
    """Time the Pallas scoring kernel (compiled, interpret=False) against
    the pure-XLA scorer on a production-shaped batch. TPU-only: on CPU
    the Mosaic path does not exist and this reports skipped."""
    from kafka_assignment_optimizer_tpu.ops.bench_kernel import kernel_vs_xla

    return kernel_vs_xla(smoke=smoke)


def run_mesh_bench(smoke: bool, seed: int) -> dict:
    """``--mesh-bench`` (docs/MESH.md, ISSUE 19): the per-bucket
    sharding search as an A/B harness. One mid bucket, every candidate
    (chains × lanes) split timed through the REAL lane dispatch path,
    each split's global winner checked bit-for-bit against the default
    — the artifact is the lanes-per-second curve across mesh widths
    plus the parity verdict. On a host whose cores are outnumbered by
    the (virtual) devices the widths timeshare the same silicon and
    throughput parity across specs is the EXPECTED result; the
    artifact stamps that so --compare reads the curve correctly."""
    from kafka_assignment_optimizer_tpu.utils.platform import pin_platform

    pin_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kafka_assignment_optimizer_tpu import build_instance
    from kafka_assignment_optimizer_tpu.parallel import mesh as pm
    from kafka_assignment_optimizer_tpu.solvers.tpu import arrays
    from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed
    from kafka_assignment_optimizer_tpu.utils import gen

    n_dev = len(jax.devices())
    lanes = 4 if smoke else 8
    n_temps = 8 if smoke else 16
    repeats = 1 if smoke else 2

    insts = []
    for i in range(lanes):
        sc = gen.adversarial(n_brokers=32, n_topics_low=3,
                             n_topics_high=3, parts_per_topic=10,
                             seed=seed + i)
        insts.append(build_instance(sc.current, sc.broker_list,
                                    sc.topology))
    ms = arrays.stack_models([arrays.from_instance(i) for i in insts])
    lane_seeds = np.stack(
        [np.asarray(greedy_seed(i), np.int32) for i in insts]
    )
    keys = jnp.stack(
        [jax.random.PRNGKey(seed + i) for i in range(lanes)]
    )
    temps = arrays.geometric_temps(2.0, 0.02, n_temps)
    bkt = (insts[0].num_brokers, insts[0].num_racks,
           int(ms.a0.shape[-2]), int(ms.a0.shape[-1]))

    pm.reset_mesh_adapt()
    t0 = time.perf_counter()
    results = pm.run_sharding_search(
        ms, lane_seeds, keys, temps, n_devices=n_dev,
        chains_per_device=2, bucket_key=bkt, repeats=repeats,
    )
    search_s = time.perf_counter() - t0
    chosen = pm.choose_sharding(bkt, n_dev, lanes)
    by_rate = {r["spec"]: r["lanes_per_s"] for r in results}
    default_spec = f"{n_dev}x1"
    best = max(results, key=lambda r: r["lanes_per_s"])
    cores = os.cpu_count() or 1
    return {
        "n_devices": n_dev,
        "lanes": lanes,
        "bucket": "x".join(str(k) for k in bkt),
        "specs": results,
        "parity_ok": all(r["parity_vs_default"] for r in results),
        "chosen": f"{chosen[0]}x{chosen[1]}",
        "default_lanes_per_s": by_rate.get(default_spec),
        "best_spec": best["spec"],
        "best_lanes_per_s": best["lanes_per_s"],
        "lane_scaling": (
            best["lanes_per_s"] / by_rate[default_spec]
            if by_rate.get(default_spec) else None
        ),
        "search_s": round(search_s, 3),
        "search_evals": pm.mesh_counters()["search_evals"],
        "host_cores": cores,
        # virtual devices timesharing fewer cores than devices: spec
        # throughput parity is expected, not a finding (docs/MESH.md)
        "single_core_parity_expected": cores < n_dev,
    }


def child_main(args: argparse.Namespace) -> int:
    if args.replay_day:
        out = run_replay_day(args.smoke, args.seed)
        print("RESULT " + json.dumps(out))
        return 0
    if args.batch_bench:
        out = run_batch_throughput(args.smoke, args.seed)
        print("RESULT " + json.dumps(out))
        return 0
    if args.portfolio_bench:
        out = run_portfolio_ab(args.smoke, args.seed)
        print("RESULT " + json.dumps(out))
        return 0
    if args.rollout_bench:
        out = run_rollout_bench(args.smoke, args.seed)
        print("RESULT " + json.dumps(out))
        return 0
    if args.decompose_bench:
        out = run_decompose_bench(args.smoke, args.seed)
        print("RESULT " + json.dumps(out))
        return 0
    if args.mesh_bench:
        out = run_mesh_bench(args.smoke, args.seed)
        print("RESULT " + json.dumps(out))
        return 0
    out = run_scenario(args.scenario, args.smoke, args.seed, args.warm)
    if args.kernel:
        try:
            out["kernel"] = run_kernel_bench(args.smoke)
        except Exception as e:  # noqa: BLE001 - kernel bench is best-effort
            out["kernel"] = {"error": repr(e)[:300]}
    print("RESULT " + json.dumps(out))
    return 0


# --------------------------------------------------------------------------


# the driver records only a ~2000-char TAIL of stdout; a line past that
# physically loses its leading fields (r3 postmortem: `parsed: null`,
# headline gone). Budget with margin; over-budget lines shed detail.
STDOUT_BUDGET = 1600

# scenarios[] rows are positional tuples to stay inside STDOUT_BUDGET;
# this schema string names the positions for the reader of the artifact.
# compile_s is cold minus best-warm (first-trace + XLA compile tax);
# cache_compiles / cache_hits are the executable-cache movement across
# the child's runs — warm runs at compiles=0 is the bucketing win.
ROW_SCHEMA = ("scenario,warm_s,cold_s,moves,min_moves_lb,feasible,"
              "proved_optimal,constructed,engine,path,compile_s,"
              "cache_compiles,cache_hits,"
              "phase_s[bounds,constructor,seed,ladder,polish,verify],"
              "pipeline_speedup,construct_host_s,"
              "dispatches_per_solve,duty_cycle,megachunk_speedup")


def _compact_row(r: dict | None, name: str, err: str | None) -> list:
    """One positional scenarios[] row (see ROW_SCHEMA): enough to audit
    every README results-table row from the artifact alone."""
    if r is None:
        return [name, None, None, None, None, 0, 0, 0, "error",
                (err or "failed")[:80], None, None, None, None, None,
                None, None, None, None]
    cache = r.get("cache") or {}
    ph = r.get("phase_s") or {}
    return [
        r["scenario"],
        r["wall_clock_s"],
        r["cold_wall_clock_s"],
        r["moves"],
        r["min_moves_lb"],
        1 if r.get("feasible") else 0,
        1 if r.get("proved_optimal") else 0,
        1 if r.get("constructed") else 0,
        r.get("engine") or "",
        r.get("construct_path") or "",
        r.get("compile_s"),
        cache.get("compiles"),
        cache.get("exec_hits"),
        # positional phase seconds (PHASE_ORDER); null = phase untimed
        [ph.get(p) for p in PHASE_ORDER] if ph else None,
        # pipeline-on/off A/B (warm search rows only): no-pipeline
        # best-warm / pipelined best-warm — >= 1.0 means the overlap
        # pays for itself in wall-clock
        r.get("pipeline_speedup"),
        # constructor host seconds: bounds_flow + greedy + reseat +
        # adopt summed from the solve report (ISSUE 10)
        r.get("construct_host_s"),
        # ladder dispatch accounting (ISSUE 17): host round trips per
        # solve, the device share of the busy wall, and the
        # chunked/fused best-warm ratio (warm search rows only)
        r.get("dispatches_per_solve"),
        r.get("duty_cycle"),
        r.get("megachunk_speedup"),
    ]


def _compact_replay(rb: dict | None, err: str | None) -> dict:
    """The replay-day block of the stdout line: the warm-vs-cold
    latency split, the per-event quality verdict, and the storm-segment
    coalescing evidence — enough to audit the ISSUE 7 acceptance
    criteria from the artifact alone."""
    if rb is None:
        return {"error": (err or "failed")[:120]}
    w, c = rb["warm"], rb["cold"]
    return {
        "events": rb["events"],
        "warm_p50_s": w["p50_s"], "warm_p99_s": w["p99_s"],
        "cold_p50_s": c["p50_s"], "cold_p99_s": c["p99_s"],
        "latency_win": rb["latency_win"],
        "quality_ok": rb["quality_ok"],
        "warm_solves": w["warm_solves"],
        "warm_certified": w["certified_events"],
        "cold_certified": c["certified_events"],
        "warm_moves": w["moves_total"], "cold_moves": c["moves_total"],
        "storm_coalesced": w["storm"]["acks_coalesced"],
        "storm_dropped": rb["storm_dropped"],
        "flight_delta_records": rb.get("flight_delta_records"),
    }


def _compact_portfolio(rp: dict | None, err: str | None) -> dict:
    """The portfolio_ab block of the stdout line: the deterministic
    quality verdict, both arms' feasible/certify counts and worst-case
    violations, first-certificate latency, and the compile counters
    that pin the one-executable-per-bucket consolidation."""
    if rp is None:
        return {"error": (err or "failed")[:120]}
    s, p = rp["single"], rp["portfolio"]
    return {
        "cases": rp["cases"],
        "quality_win": rp["quality_win"],
        "feasible_single": s["feasible"],
        "feasible_portfolio": p["feasible"],
        "certified_single": s["certified"],
        "certified_portfolio": p["certified"],
        "worst_viol_single": s["worst_violations"],
        "worst_viol_portfolio": p["worst_violations"],
        "early_exit": p["early_exit"],
        "ttfc_p50_s": p["ttfc_p50_s"],
        "wall_p50_single_s": s["wall_p50_s"],
        "wall_p50_portfolio_s": p["wall_p50_s"],
        "compiles_portfolio_arm": rp["compiles_portfolio_arm"],
    }


def _compact_decompose(rd: dict | None, err: str | None) -> dict:
    """The decompose block of the stdout line: the ultra-jumbo cold
    wall, the decomposed-vs-flat speedup, sub-problem count, bound gap
    and the deterministic quality keys (``stitched_feasible``,
    ``gap_ok``) — the ISSUE 16 bench evidence, compare-gated by
    obs/regress.py."""
    if rd is None:
        return {"error": (err or "failed")[:120]}
    return {k: rd[k] for k in (
        "ultra_parts", "ultra_jumbo_cold_s", "sub_problems",
        "iterations", "bound_gap", "certified", "stitched_feasible",
        "gap_ok", "cmp_parts", "decomposed_wall_s", "flat_wall_s",
        "decompose_speedup",
    )}


def _compact_mesh(rm: dict | None, err: str | None) -> dict:
    """The mesh block of the stdout line: the lanes-per-second curve
    across (chains × lanes) splits, the bit-parity verdict, the
    evidence-table choice, and the single-core-parity stamp — the
    ISSUE 19 bench evidence, compare-gated by obs/regress.py."""
    if rm is None:
        return {"error": (err or "failed")[:120]}
    out = {k: rm[k] for k in (
        "n_devices", "lanes", "bucket", "parity_ok", "chosen",
        "default_lanes_per_s", "best_spec", "best_lanes_per_s",
        "lane_scaling", "search_s", "search_evals",
        "single_core_parity_expected",
    )}
    # the full curve, compacted: spec -> lanes/s
    out["curve"] = {r["spec"]: round(r["lanes_per_s"], 3)
                    for r in rm.get("specs", ())}
    return out


def _compact_rollout(rr: dict | None, err: str | None) -> dict:
    """The rollout block of the stdout line: waves to completion, the
    independently-recomputed per-wave peaks vs caps, the mid-rollout
    re-plan latency, and the terminal verdict — the ISSUE 12 bench
    evidence, compare-gated by obs/regress.py."""
    if rr is None:
        return {"error": (err or "failed")[:120]}
    return {k: rr[k] for k in (
        "waves_planned", "waves_applied", "replans",
        "broker_cap", "rack_cap", "peak_broker", "peak_rack",
        "caps_ok", "terminal", "terminal_ok",
        "pack_s", "replan_s", "total_s",
    )}


def _compact_kernel(k: dict) -> dict:
    """3-6 scalars from the kernel micro-bench; the full block (roofline
    models, propose timings) goes to stderr with the rest of the detail."""
    if not isinstance(k, dict):
        return {"error": str(k)[:120]}
    out: dict = {}
    if "error" in k:
        out["error"] = str(k["error"])[:120]
    if "skipped" in k:
        out["skipped"] = True
    for src, dst in (
        ("pallas_candidates_per_s", "pallas_cand_s"),
        ("xla_candidates_per_s", "xla_cand_s"),
        ("pallas_speedup_vs_xla", "speedup"),
        ("pallas_parity", "parity"),
        ("sweep_ms", "sweep_ms"),
    ):
        if src in k:
            out[dst] = k[src]
    roof = k.get("roofline") or {}
    if "hbm_utilization" in roof:
        out["hbm_util"] = roof["hbm_utilization"]
    if "compute_utilization" in roof:
        out["compute_util"] = roof["compute_utilization"]
    sweep_roof = k.get("sweep_roofline") or {}
    if "compute_utilization" in sweep_roof:
        # rescoring-component floor per sweep: a lower bound
        out["sweep_compute_util_lb"] = sweep_roof["compute_utilization"]
    return out


def _print_final(line: dict) -> None:
    """Emit the ONE stdout line, shedding optional detail if it would
    overflow the driver's tail capture. Never raises."""
    for drop in ((), ("search_cold_runs",), ("jumbo_cold_runs",),
                 ("kernel",), ("bucket_reuse",), ("replay_day",),
                 ("batch_throughput",),
                 ("search_cold_medians", "jumbo_cold_median_s"),
                 ("scenarios", "rows_schema")):
        for key in drop:
            line.pop(key, None)
        s = json.dumps(line)
        if len(s) <= STDOUT_BUDGET:
            break
    print(s)
    print(f"[bench] final stdout line: {len(s)} bytes", file=sys.stderr)


def emit(head: dict | None, platform: str, tpu_error: str | None,
         scenario: str, run_error: str | None = None,
         scenarios: list[list] | None = None,
         cold_cached: float | None = None,
         jumbo_runs: list[float] | None = None,
         search_cold_runs: dict | None = None,
         bucket_reuse: dict | None = None,
         batch_throughput: dict | None = None,
         replay_day: dict | None = None,
         portfolio_ab: dict | None = None,
         decompose: dict | None = None,
         megachunk_ab: dict | None = None,
         env_stamp: dict | None = None) -> None:
    """Print full detail to stderr, then ONE compact stdout JSON line."""
    if head is None:
        line = {
            "metric": f"{scenario}_wall_clock",
            "value": 0.0,
            "unit": "s",
            "vs_baseline": 0.0,
            "platform": platform,
            "error": (run_error or tpu_error or "unknown failure")[:300],
        }
        if env_stamp:
            line["env"] = env_stamp
        if tpu_error and run_error:
            line["tpu_error"] = tpu_error[:200]
        if scenarios:
            line["rows_schema"] = ROW_SCHEMA
            line["scenarios"] = scenarios
        _print_final(line)
        return
    # the full child report (incl. roofline blocks) is stderr-only
    print("[bench] DETAIL " + json.dumps(head), file=sys.stderr)
    error = tpu_error
    # quality gate: feasible, and moves at the provable minimum when the
    # bound is known achievable (a fast wrong answer scores nothing)
    quality_ok = head["feasible"] and (
        not head["lb_tight"] or head["moves"] <= head["min_moves_lb"]
    )
    wall = head["wall_clock_s"]
    vs = round(BASELINE_BUDGET_S / wall, 3) if quality_ok and wall > 0 else 0.0
    line = {
        "metric": (
            f"{head['scenario']}_{head['brokers']}b_{head['partitions']}p"
            "_warm_wall_clock"
        ),
        "value": wall,
        "unit": "s",
        "vs_baseline": vs,
        "platform": head.get("platform", platform),
        "cold_wall_clock_s": head.get("cold_wall_clock_s"),
        "moves": head["moves"],
        "min_moves_lb": head["min_moves_lb"],
        "feasible": head["feasible"],
        "proved_optimal": head.get("proved_optimal"),
        "engine": head.get("engine"),
    }
    if env_stamp:
        # the comparability stamp rides EVERY artifact (never shed by
        # _print_final): obs/regress.py gates on it
        line["env"] = env_stamp
    if cold_cached is not None:
        # a FRESH process re-solving the headline against the populated
        # persistent compile cache: the cold start a second process on
        # this host actually pays (VERDICT r2 item 2)
        line["cold_cached_wall_clock_s"] = cold_cached
    if head.get("pallas_fallback"):
        line["pallas_fallback"] = head["pallas_fallback"]
    if error:
        line["tpu_error"] = error[:200]  # why no accelerator was used
    if scenarios:
        # the full results table inside the driver artifact, one
        # positional row per BASELINE scenario (VERDICT r2 item 3 /
        # r3 item 1: must fit the tail capture whole)
        line["rows_schema"] = ROW_SCHEMA
        line["scenarios"] = scenarios
    if jumbo_runs:
        # repeated fresh-process jumbo solves: the variance-discipline
        # evidence (VERDICT r3 item 3 — bounded time AND spread), with
        # the MEDIAN alongside (ISSUE 10): the 7.4-13.1 s spread of
        # BENCH_r05 made the headline first-run draw the artifact
        # value — the median is the stable statistic readers should
        # quote, and obs/regress.py already compares on it
        line["jumbo_cold_runs"] = jumbo_runs
        line["jumbo_cold_median_s"] = _median(jumbo_runs)
    if search_cold_runs:
        # sweep-path cold starts, 3 fresh processes each (run 0 =
        # empty compile cache; later runs pay the cache-warm cold every
        # subsequent process on this host sees — VERDICT r4 item 2)
        line["search_cold_runs"] = search_cold_runs
        line["search_cold_medians"] = {
            k: _median(v) for k, v in search_cold_runs.items()
        }
    if bucket_reuse:
        # a DIFFERENT cluster mapping to an already-compiled bucket:
        # compiles == 0 / cache_hit true is the shape-bucketing
        # acceptance evidence
        line["bucket_reuse"] = bucket_reuse
    if batch_throughput:
        # batched-lane throughput: solves/s at B in {1,2,4,8} same-bucket
        # instances + B=8-vs-sequential speedup + per-lane quality flags
        line["batch_throughput"] = batch_throughput
    if replay_day:
        # event-day replay: warm delta solves vs cold re-solves over
        # one scripted day — p50/p99 latency split, per-event quality,
        # storm coalescing with zero drops (docs/WATCH.md)
        line["replay_day"] = replay_day
    if portfolio_ab:
        # portfolio A/B: worst-case quality at equal budget,
        # portfolio-on vs single-config (docs/PORTFOLIO.md)
        line["portfolio_ab"] = portfolio_ab
    if decompose:
        # decomposed map-reduce rung: ultra-jumbo cold wall,
        # decomposed-vs-flat speedup, certificate-or-gap verdict
        # (docs/DECOMPOSE.md)
        line["decompose"] = decompose
    if megachunk_ab:
        # fused-megachunk A/B (ISSUE 17): chunked-vs-fused warm walls,
        # dispatch reduction at K=8, fused duty cycle, and the
        # bit-identical-plan parity verdict (docs/PIPELINE.md)
        line["megachunk_ab"] = megachunk_ab
    if "device_sampler" in head:
        # device-occupancy evidence for the headline run: duty cycle,
        # per-device memory, and the sampler's measured overhead
        # (docs/OBSERVABILITY.md "Fleet plane")
        line["device_sampler"] = head["device_sampler"]
    if "profile" in head:
        # roofline/attribution columns (obs.prof): the dominant
        # executable's cost model + achieved occupancy and the last
        # solve's ledger shares — never shed, obs/regress.py's
        # efficiency gate compares these between artifacts
        line["profile"] = head["profile"]
    if "kernel" in head:
        line["kernel"] = _compact_kernel(head["kernel"])
    _print_final(line)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scenario", default="decommission",
                    help="headline scenario (default: decommission)")
    ap.add_argument("--all", action="store_true", default=True,
                    help="run every BASELINE scenario (default; the "
                         "stdout line carries the full scenarios array)")
    ap.add_argument("--headline-only", action="store_true",
                    help="run only the headline scenario")
    ap.add_argument("--smoke", action="store_true", help="tiny instances")
    ap.add_argument("--only", default=None, metavar="S1,S2,...",
                    help="run ONLY the named scenarios, cold, skipping "
                         "every extra (kernel, batch throughput, "
                         "replay day, repeated cold runs). The first "
                         "name is the headline. Built for the CI "
                         "cold-path step: the lp/construct-dominated "
                         "scenarios twice, then bench.py --compare "
                         "(docs/CONSTRUCTOR.md)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--sample-devices", type=float, default=None,
                    metavar="HZ",
                    help="arm the device-occupancy sampler "
                         "(obs.sampler) in every solve child at this "
                         "rate; the headline row gains a "
                         "device_sampler block (duty cycle, HBM "
                         "bytes, measured sampler overhead)")
    ap.add_argument("--kernel", action="store_true",
                    help="also time Pallas kernel vs XLA scorer "
                         "(auto-enabled when the backend is TPU)")
    ap.add_argument("--no-kernel", action="store_true",
                    help="suppress the auto-enabled kernel micro-bench")
    ap.add_argument("--compare", nargs=2, metavar=("OLD", "NEW"),
                    default=None,
                    help="perf-regression gate (docs/OBSERVABILITY.md):"
                         " diff two bench artifacts with noise-aware "
                         "ratio thresholds and median-of-N aggregation;"
                         " prints the verdict JSON and exits 0 ok / "
                         "2 unreadable-artifact / 3 regression / "
                         "4 incomparable-environments. Runs no solves.")
    ap.add_argument("--compare-force", action="store_true",
                    help="with --compare: proceed despite missing or "
                         "mismatched env stamps")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--warm", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--batch-bench", action="store_true",
                    help="also run the batched-lane throughput scenario "
                         "(B in {1,2,4,8} same-bucket instances; "
                         "auto-enabled with --all)")
    ap.add_argument("--portfolio-bench", action="store_true",
                    help="run ONLY the portfolio A/B scenario "
                         "(docs/PORTFOLIO.md): the messy worst-case "
                         "family, portfolio-on vs single-config at "
                         "equal budget — quality delta, certify rate, "
                         "time-to-first-certificate, exec-cache "
                         "compile counters — emitted as a one-line "
                         "portfolio_ab artifact (the soak cold-path "
                         "step's entry; same exclusive convention as "
                         "--replay-day). The full default sweep runs "
                         "the same harness automatically as an extra.")
    ap.add_argument("--rollout-bench", action="store_true",
                    help="run ONLY the streaming-rollout harness "
                         "(docs/ROLLOUT.md): one supervised rollout "
                         "through the watch registry on the real "
                         "delta-solve path — waves-to-completion "
                         "under tight caps, per-wave peak broker/rack "
                         "transfer vs cap recomputed off the move "
                         "graph, and the re-plan latency after a "
                         "mid-rollout broker loss; emitted as a "
                         "one-line rollout artifact wired into "
                         "--compare regression keys (same exclusive "
                         "convention as --replay-day)")
    ap.add_argument("--decompose-bench", action="store_true",
                    help="run ONLY the map-reduce decomposition "
                         "scenario (docs/DECOMPOSE.md): the ultra-"
                         "jumbo AZ-structured case solved through the "
                         "decomposed rung — cold wall, sub-problem "
                         "count, certificate-or-bound-gap verdict, "
                         "oracle-checked stitched feasibility, and "
                         "the decomposed-vs-flat speedup at a size "
                         "both paths can solve — emitted as a "
                         "one-line decompose artifact wired into "
                         "--compare regression keys (same exclusive "
                         "convention as --replay-day)")
    ap.add_argument("--mesh-bench", action="store_true",
                    help="run ONLY the sharded-mesh A/B harness "
                         "(docs/MESH.md): the per-bucket sharding "
                         "search over every (chains x lanes) split of "
                         "one mid bucket through the real lane "
                         "dispatch path — per-spec lanes/s, bit-"
                         "parity verdict vs the default split, the "
                         "evidence-table choice; emitted as a "
                         "one-line mesh artifact wired into "
                         "--compare regression keys (same exclusive "
                         "convention as --replay-day). On CPU the "
                         "child is forced to 8 virtual devices")
    ap.add_argument("--fleet-bench", action="store_true",
                    help="run ONLY the fleet-router harness "
                         "(docs/FLEET.md): spawn a kao-router + 2 "
                         "REAL serve workers sharing one fresh "
                         "KAO_COMPILE_CACHE, fleet-warm the bucket "
                         "ladder through the router (each bucket "
                         "compiles exactly once fleet-wide), then "
                         "drive an identical mixed-bucket load "
                         "through the fleet and through a fresh "
                         "single worker — aggregate solves/s, "
                         "p50/p99, affinity hit rate, and the "
                         "shared-cache warmup accounting; emitted as "
                         "a one-line fleet artifact wired into "
                         "--compare regression keys (same exclusive "
                         "convention as --replay-day)")
    ap.add_argument("--fleet-workers", type=int, default=2,
                    metavar="N",
                    help="worker processes for --fleet-bench "
                         "(default 2)")
    ap.add_argument("--replay-day", action="store_true",
                    help="run ONLY the event-day replay harness "
                         "(docs/WATCH.md): a scripted day of cluster "
                         "events — rolling decommission, partition "
                         "growth, rack loss + recovery, an event "
                         "storm — through the watch state machine on "
                         "the warm product path, with a paired shadow "
                         "cold solve of the identical state at every "
                         "sequential event, reporting p50/p99 "
                         "per-event latency, plan quality, and storm "
                         "coalescing with zero drops")
    args = ap.parse_args()

    if args.child:
        return child_main(args)

    if args.compare:
        # the perf-regression gate: pure artifact diffing, no solves,
        # no jax — safe in the parent process by construction
        from kafka_assignment_optimizer_tpu.obs import regress

        return regress.run_compare(args.compare[0], args.compare[1],
                                   force=args.compare_force)

    if args.replay_day:
        # standalone replay-day mode (the soak smoke job's entry): one
        # child, one dedicated stdout line — no scenario sweep
        try:
            env, platform, tpu_err, ndev = resolve_backend()
        except Exception as e:  # noqa: BLE001 - must emit something
            print(json.dumps({"metric": "replay_day", "error": repr(e)[:300]}))
            return 0
        rb, eb = _run_child(args, "replay_day", env, warmrun=False,
                            replay_day=True)
        if rb is not None:
            print("[bench] REPLAY " + json.dumps(rb), file=sys.stderr)
        line = {"metric": "replay_day", "platform": platform,
                "env": _env_stamp(platform, ndev, env),
                **_compact_replay(rb, eb)}
        if tpu_err:
            line["tpu_error"] = tpu_err[:200]
        print(json.dumps(line))
        return 0

    if args.fleet_bench:
        # standalone fleet-router harness (docs/FLEET.md): the parent
        # stays jax-free — every solve runs inside REAL worker
        # subprocesses, so no child hop is needed here
        try:
            env, platform, tpu_err, ndev = resolve_backend()
        except Exception as e:  # noqa: BLE001 - must emit something
            print(json.dumps({"metric": "fleet_bench",
                              "error": repr(e)[:300]}))
            return 0
        try:
            fb = run_fleet_bench(args.smoke, args.seed, env,
                                 n_workers=max(1, args.fleet_workers))
            ef = None
        except Exception as e:  # noqa: BLE001 - must emit something
            fb, ef = None, repr(e)[:300]
        if fb is not None:
            print("[bench] FLEET " + json.dumps(fb), file=sys.stderr)
        line = {"metric": "fleet_bench", "platform": platform,
                "env": _env_stamp(platform, ndev, env),
                "fleet": fb if fb is not None
                else {"error": ef or "failed"}}
        if tpu_err:
            line["tpu_error"] = tpu_err[:200]
        print(json.dumps(line))
        return 0

    if args.rollout_bench:
        # standalone rollout harness (the soak rollout step's entry):
        # one child, one dedicated stdout line — no scenario sweep
        try:
            env, platform, tpu_err, ndev = resolve_backend()
        except Exception as e:  # noqa: BLE001 - must emit something
            print(json.dumps({"metric": "rollout_bench",
                              "error": repr(e)[:300]}))
            return 0
        rr, er = _run_child(args, "rollout_bench", env, warmrun=False,
                            rollout_bench=True)
        if rr is not None:
            print("[bench] ROLLOUT " + json.dumps(rr), file=sys.stderr)
        line = {"metric": "rollout_bench", "platform": platform,
                "env": _env_stamp(platform, ndev, env),
                "rollout": _compact_rollout(rr, er)}
        if tpu_err:
            line["tpu_error"] = tpu_err[:200]
        print(json.dumps(line))
        return 0

    if args.decompose_bench:
        # standalone decomposition harness (the soak decomposition
        # step's entry): one child, one dedicated stdout line — no
        # scenario sweep
        try:
            env, platform, tpu_err, ndev = resolve_backend()
        except Exception as e:  # noqa: BLE001 - must emit something
            print(json.dumps({"metric": "decompose_bench",
                              "error": repr(e)[:300]}))
            return 0
        rd, ed = _run_child(args, "decompose_bench", env, warmrun=False,
                            decompose_bench=True)
        if rd is not None:
            print("[bench] DECOMPOSE " + json.dumps(rd), file=sys.stderr)
        line = {"metric": "decompose_bench", "platform": platform,
                "env": _env_stamp(platform, ndev, env),
                "decompose": _compact_decompose(rd, ed)}
        if tpu_err:
            line["tpu_error"] = tpu_err[:200]
        print(json.dumps(line))
        return 0

    if args.mesh_bench:
        # standalone sharded-mesh harness (the soak mesh step's entry):
        # one child, one dedicated stdout line — no scenario sweep. On
        # CPU the split space is empty without virtual devices, so the
        # child gets the same 8-device forcing the test suite uses.
        try:
            env, platform, tpu_err, ndev = resolve_backend()
        except Exception as e:  # noqa: BLE001 - must emit something
            print(json.dumps({"metric": "mesh_bench",
                              "error": repr(e)[:300]}))
            return 0
        if platform == "cpu" and "xla_force_host_platform_device_count" \
                not in env.get("XLA_FLAGS", ""):
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_count=8")
        rm, em = _run_child(args, "mesh_bench", env, warmrun=False,
                            mesh_bench=True)
        if rm is not None:
            print("[bench] MESH " + json.dumps(rm), file=sys.stderr)
        line = {"metric": "mesh_bench", "platform": platform,
                "env": _env_stamp(platform, ndev, env),
                "mesh_bench": _compact_mesh(rm, em)}
        if tpu_err:
            line["tpu_error"] = tpu_err[:200]
        print(json.dumps(line))
        return 0

    if args.portfolio_bench:
        # standalone portfolio A/B (the soak cold-path step's entry):
        # one child, one dedicated stdout line — no scenario sweep.
        # The full --all sweep runs the same harness as an extra.
        try:
            env, platform, tpu_err, ndev = resolve_backend()
        except Exception as e:  # noqa: BLE001 - must emit something
            print(json.dumps({"metric": "portfolio_ab",
                              "error": repr(e)[:300]}))
            return 0
        rp, ep = _run_child(args, "portfolio_ab", env, warmrun=False,
                            portfolio_bench=True)
        if rp is not None:
            print("[bench] PORTFOLIO " + json.dumps(rp), file=sys.stderr)
        line = {"metric": "portfolio_ab", "platform": platform,
                "env": _env_stamp(platform, ndev, env),
                "portfolio_ab": _compact_portfolio(rp, ep)}
        if tpu_err:
            line["tpu_error"] = tpu_err[:200]
        print(json.dumps(line))
        return 0

    try:
        env, platform, tpu_err, ndev = resolve_backend()
    except Exception as e:  # noqa: BLE001 - must never die before emitting
        emit(None, "unknown", f"backend resolution failed: {e!r}",
             args.scenario)
        return 0
    if args.sample_devices:
        # thread the sampler rate into every solve child (the parent
        # never initializes a backend, so it never samples itself)
        env["KAO_SAMPLE_DEVICES"] = str(args.sample_devices)
    print(f"[bench] platform={platform}"
          + (f" (accelerator unavailable: {tpu_err})" if tpu_err else ""),
          file=sys.stderr)
    only_names: list[str] | None = None
    if args.only:
        from kafka_assignment_optimizer_tpu.utils import gen

        only_names = [s.strip() for s in args.only.split(",") if s.strip()]
        unknown = [n for n in only_names if n not in gen.SCENARIOS]
        if unknown or not only_names:
            print(json.dumps({
                "metric": "bench_only", "value": 0.0, "unit": "s",
                "vs_baseline": 0.0, "platform": platform,
                "error": f"unknown --only scenarios {unknown}",
            }))
            return 0
        args.scenario = only_names[0]
    # kernel evidence must land in every TPU round's artifact (VERDICT r1
    # item 2), so the micro-bench is opt-out, not opt-in, on TPU —
    # except under --only, whose contract is "scenario rows, nothing
    # else, as fast as possible"
    if platform == "tpu" and not args.no_kernel and not only_names:
        args.kernel = True

    if args.headline_only:
        args.all = False
    # extras (cold-cached re-run, repeated jumbo/search cold runs,
    # replay day, batch throughput) accompany the full sweep only
    extras = args.all and not only_names
    if only_names:
        names = only_names
    elif args.all:
        # importing the package is safe in the parent — the robustness
        # invariant is that the parent never *initializes* a jax backend
        # (jax.devices() is what hangs/fails, not `import jax`)
        from kafka_assignment_optimizer_tpu.utils import gen

        names = [args.scenario] + [
            n for n in gen.SCENARIOS if n != args.scenario
        ]
    else:
        names = [args.scenario]
    head, head_err = None, None
    rows: list[list] = []
    cold_cached: float | None = None
    bucket_reuse: dict | None = None
    megachunk_ab: dict | None = None
    for name in names:
        is_head = name == args.scenario
        # the adversarial rows are the at-scale proof of the SEARCH
        # engine (VERDICT r3 item 2; adv50k extends it to 5x) and their
        # budget is a WARM number — two extra warm runs (~2 s at 10k,
        # ~15 s at 50k) buy the artifact a warm-vs-cold split like the
        # headline's. --only runs everything cold: its consumers (the
        # CI cold-path gate) compare cold wall clocks.
        warmrun = (
            is_head or name in ("adversarial", "adv50k")
        ) and not only_names
        r, err = _run_child(args, name, env, warmrun=warmrun,
                            kernel=is_head)
        if r is None and platform != "cpu":
            # accelerator succeeded at probe time but died mid-run:
            # one CPU retry so the harness still lands a number. Only the
            # headline's fallback is reported as tpu_error — a flaky
            # side-scenario must not mislabel a successful headline run.
            cpu_env = dict(env)
            cpu_env["JAX_PLATFORMS"] = "cpu"
            r2, err2 = _run_child(args, name, cpu_env, warmrun=warmrun,
                                  kernel=is_head)
            if r2 is not None:
                if is_head:
                    tpu_err = tpu_err or err
                r, err = r2, err2
        rows.append(_compact_row(r, name, err))
        if r is not None and r.get("bucket_reuse") and bucket_reuse is None:
            bucket_reuse = r["bucket_reuse"]
        if r is not None and r.get("megachunk_ab") and megachunk_ab is None:
            megachunk_ab = r["megachunk_ab"]
        if args.all:
            print(json.dumps(r if r is not None else {"scenario": name,
                                                      "error": err}),
                  file=sys.stderr)
        if is_head:
            head, head_err = r, err
            if r is not None and extras:
                # the headline child just populated the persistent
                # compile cache: measure what a FRESH process pays now
                # (the operationally honest cold number — every CLI /
                # service / bench invocation is its own process).
                # Skipped under --headline-only: that flag exists for
                # quick single-scenario runs.
                rc, _err_c = _run_child(args, name, env, warmrun=False)
                if rc is not None:
                    cold_cached = rc["cold_wall_clock_s"]

    jumbo_runs: list[float] | None = None
    search_cold_runs: dict[str, list] | None = None
    if extras:
        # variance discipline on the certification-heavy jumbo config:
        # 4 more FRESH processes (cold each) so the artifact carries 5
        # repeated runs, not a single lucky draw (VERDICT r3 item 3)
        jrow = next((r for r in rows if r and r[0] == "jumbo"), None)
        if jrow is not None and jrow[2] is not None:
            jumbo_runs = [jrow[2]]
            for _ in range(4):
                rj, _ej = _run_child(args, "jumbo", env, warmrun=False)
                if rj is None:
                    break
                jumbo_runs.append(rj["cold_wall_clock_s"])
        # the same discipline on the sweep-path cold start (VERDICT r4
        # items 2-3): the first adversarial/adv50k child populated the
        # persistent compile cache, so two more FRESH processes measure
        # the cold start every later process on this host actually pays
        # (run 0 = empty-cache cold from the first child)
        search_cold_runs = {}
        for sname in ("adversarial", "adv50k"):
            srow = next((r for r in rows if r and r[0] == sname), None)
            if srow is None or srow[2] is None:
                continue
            runs = [srow[2]]
            for _ in range(2):
                rs, _es = _run_child(args, sname, env, warmrun=False)
                if rs is None:
                    break
                runs.append(rs["cold_wall_clock_s"])
            search_cold_runs[sname] = runs
        search_cold_runs = search_cold_runs or None

    replay_day: dict | None = None
    if extras:
        # the event-day replay (ISSUE 7 tentpole evidence): warm delta
        # solves vs cold re-solves over the same scripted day of
        # cluster events, compacted to the latency/quality/coalescing
        # verdict for stdout
        rr, er = _run_child(args, "replay_day", env, warmrun=False,
                            replay_day=True)
        if rr is not None:
            print("[bench] REPLAY " + json.dumps(rr), file=sys.stderr)
        replay_day = _compact_replay(rr, er)

    portfolio_ab: dict | None = None
    if extras:
        # the portfolio A/B (PR-11 tentpole evidence): worst-case
        # quality at equal budget, portfolio-on vs single-config,
        # compacted to the quality/certify/ttfc verdict for stdout
        rp, ep = _run_child(args, "portfolio_ab", env, warmrun=False,
                            portfolio_bench=True)
        if rp is not None:
            print("[bench] PORTFOLIO " + json.dumps(rp), file=sys.stderr)
        portfolio_ab = _compact_portfolio(rp, ep)

    decompose: dict | None = None
    if extras:
        # the map-reduce decomposition rung (PR-16 tentpole evidence):
        # ultra-jumbo cold wall through the decomposed path, sub-problem
        # count, certificate-or-gap verdict, and decomposed-vs-flat
        # speedup, compacted for stdout
        rd, ed = _run_child(args, "decompose_bench", env, warmrun=False,
                            decompose_bench=True)
        if rd is not None:
            print("[bench] DECOMPOSE " + json.dumps(rd), file=sys.stderr)
        decompose = _compact_decompose(rd, ed)

    batch_throughput: dict | None = None
    if extras or args.batch_bench:
        # the batched-lane throughput scenario (PR-2 tentpole evidence):
        # one child, B in {1,2,4,8} same-bucket instances; compacted to
        # the per-width solves/s + speedup + quality flags for stdout
        rb, eb = _run_child(args, "batch_throughput", env, warmrun=False,
                            batch_bench=True)
        if rb is not None:
            print("[bench] BATCH " + json.dumps(rb), file=sys.stderr)
            batch_throughput = {
                **{k: v["solves_per_s"] for k, v in rb["widths"].items()},
                "speedup_b8": rb["speedup_b8_vs_seq"],
                "lanes_feasible": rb["lanes_feasible"],
                "moves_at_bound": rb["moves_at_bound"],
            }
        else:
            batch_throughput = {"error": (eb or "failed")[:120]}

    emit(head, platform, tpu_err, args.scenario, head_err,
         scenarios=rows if (args.all or only_names) else None,
         cold_cached=cold_cached,
         jumbo_runs=jumbo_runs, search_cold_runs=search_cold_runs,
         bucket_reuse=bucket_reuse, batch_throughput=batch_throughput,
         replay_day=replay_day, portfolio_ab=portfolio_ab,
         decompose=decompose, megachunk_ab=megachunk_ab,
         env_stamp=_env_stamp(platform, ndev, env))
    return 0


if __name__ == "__main__":
    sys.exit(main())
