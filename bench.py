#!/usr/bin/env python
"""Benchmark harness — the north-star scenario (BASELINE.json).

Runs the headline configuration (256 brokers / 8 racks / 10k partitions /
RF=3, single-broker decommission) through the TPU annealing backend and
prints ONE JSON line:

    {"metric": ..., "value": <wall_clock_s>, "unit": "s", "vs_baseline": ...}

``vs_baseline`` is the speed-up vs the north-star budget of 5 s
(BASELINE.json: "<= lp_solve's move count in <5s wall-clock"), gated on
plan quality: if the plan is infeasible, or moves exceed the provable
minimum (the replicas hosted by the decommissioned broker), vs_baseline is
reported as 0.0 — a fast wrong answer scores nothing.

Flags: ``--scenario`` picks another BASELINE config, ``--smoke`` shrinks
the instance for quick CPU checks, ``--all`` prints per-scenario results
to stderr before the headline line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def run_scenario(
    name: str, smoke: bool = False, seed: int = 0, warm: bool = False
) -> dict:
    from kafka_assignment_optimizer_tpu.utils.platform import pin_platform

    pin_platform()
    from kafka_assignment_optimizer_tpu.api import optimize
    from kafka_assignment_optimizer_tpu.utils import gen

    if smoke:
        shrunk = {
            "demo": dict(),
            "scale_out": dict(n_old=12, n_new=16, n_topics=8, parts_per_topic=10),
            "decommission": dict(n_brokers=32, n_topics=8, parts_per_topic=25),
            "rf_change": dict(n_brokers=16, n_topics=4, parts_per_topic=25),
            "leader_only": dict(n_brokers=32, n_topics=8, parts_per_topic=25),
        }
        sc = gen.SCENARIOS[name](**shrunk[name])
    else:
        sc = gen.SCENARIOS[name]()

    runs = 2 if warm else 1  # warm: time the second run (XLA caches the jit)
    for _ in range(runs):
        t0 = time.perf_counter()
        res = optimize(solver="tpu", seed=seed, **sc.kwargs)
        wall = time.perf_counter() - t0
    report = res.report()
    return {
        "scenario": sc.name,
        # end-to-end optimize() time: parse -> model -> solve -> decode -> diff
        "wall_clock_s": round(wall, 3),
        "solver_s": report["solver_wall_clock_s"],
        "warm": warm,
        "moves": report["replica_moves"],
        "min_moves_lb": sc.min_moves_lb,
        "lb_tight": sc.lb_tight,
        "leader_changes": report["leader_changes"],
        "feasible": report["feasible"],
        "objective": report["objective_weight"],
        "objective_ub": report["objective_upper_bound"],
        "brokers": report["brokers"],
        "partitions": report["partitions"],
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scenario", default="decommission",
                    help="headline scenario (default: decommission)")
    ap.add_argument("--all", action="store_true",
                    help="run every BASELINE scenario (extras to stderr)")
    ap.add_argument("--smoke", action="store_true", help="tiny instances")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    from kafka_assignment_optimizer_tpu.utils import gen

    names = list(gen.SCENARIOS) if args.all else [args.scenario]
    results = {}
    for name in names:
        r = run_scenario(
            name, smoke=args.smoke, seed=args.seed, warm=name == args.scenario
        )
        results[name] = r
        if args.all:
            print(json.dumps(r), file=sys.stderr)

    head = results[args.scenario]
    baseline_s = 5.0  # north-star budget (BASELINE.json)
    # quality gate: feasible, and moves at the provable minimum when the
    # bound is known achievable (a fast wrong answer scores nothing)
    quality_ok = head["feasible"] and (
        not head["lb_tight"] or head["moves"] <= head["min_moves_lb"]
    )
    wall = head["wall_clock_s"]
    vs = round(baseline_s / wall, 3) if quality_ok and wall > 0 else 0.0
    line = {
        "metric": f"{head['scenario']}_{head['brokers']}b_{head['partitions']}p_warm_wall_clock",
        "value": wall,
        "unit": "s",
        "vs_baseline": vs,
        "moves": head["moves"],
        "min_moves_lb": head["min_moves_lb"],
        "feasible": head["feasible"],
    }
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
