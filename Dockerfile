# Optimizer-as-a-service (the reference runs a hosted POST /submit
# instance; /root/reference/README.md:187-195). CPU image — the JAX CPU
# backend runs the identical solve path; on a TPU VM install the
# matching jax[tpu] wheel instead.
FROM python:3.12-slim

# g++ for the self-building native backends (exact C++ B&B + the
# bundled lp_solve-compatible CLI); lp-solve is the REAL lp_solve 5.5
# CLI — the reference's actual solver (README.md:135-137) — so
# --solver=lp_solve runs the genuine binary in this image (a system
# lp_solve on PATH takes precedence over the bundled work-alike), and
# tests/test_lp_solve_cli.py::test_real_lp_solve_binary_parity
# executes against it (it skips where the binary is absent)
RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ lp-solve \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY kafka_assignment_optimizer_tpu ./kafka_assignment_optimizer_tpu
RUN pip install --no-cache-dir .[milp]

# non-root; the compile cache and native build cache live under /tmp
ENV KAO_JIT_CACHE=/tmp/kao-jit-cache \
    XDG_CACHE_HOME=/tmp/cache
USER nobody

EXPOSE 8787
# saturation shedding and the per-solve cap are on by default; tune via
# --lock-wait-s / --max-solve-s
ENTRYPOINT ["kafka-assignment-optimizer-serve", "--host", "0.0.0.0", \
            "--port", "8787"]
