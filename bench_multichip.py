"""Multi-chip scaling-benefit curve (VERDICT r4 item 6).

The driver's ``dryrun_multichip`` proves the sharded solve COMPILES,
EXECUTES, and delivers ICI migration on an N-device mesh; this script
measures what extra devices BUY. On the virtual 8-device CPU mesh
(the same no-cluster strategy the test suite uses), it runs the sweep
engine on the adversarial instance — the one benchmark class where the
constructors refuse and search quality is the product — at FIXED
per-chain sweep budget for n_devices in {1, 2, 4, 8}, and records the
population-best objective/moves per device count.

The mesh axis is candidate-batch data parallelism: devices multiply
CHAINS (independent annealing trajectories + once-per-snapshot ICI
best-migration), not partitions, so the expected benefit is a better
best-of-population at ~constant wall per sweep on real hardware (each
chip anneals its own chains; the only cross-chip traffic is the few-KB
winner broadcast). On this 1-core CPU host the virtual devices
timeshare, so wall grows with devices here — the quality column is the
hardware-independent signal, the wall column is NOT what a v5e-8 would
show (see docs/DESIGN.md).

Usage: ``python bench_multichip.py [--sweeps N] [--chains-per-device N]
[--smoke]`` — prints one JSON object; the driver-independent artifact
is committed as ``MULTICHIP_CURVE_r05.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    # short-budget regime on purpose: extra devices buy quality exactly
    # when the per-chain budget does NOT saturate the instance; a
    # budget where 2 chains already hit the plateau shows a flat curve
    ap.add_argument("--sweeps", type=int, default=32)
    ap.add_argument("--chains-per-device", type=int, default=2)
    ap.add_argument("--scramble-seed", type=int, default=0,
                    help="RNG seed for the leadership scramble")
    ap.add_argument("--smoke", action="store_true", default=True,
                    help="smoke-sized adversarial instance (default: "
                         "the full 10k-partition instance needs a real "
                         "accelerator to finish in reasonable time)")
    ap.add_argument("--full", dest="smoke", action="store_false")
    args = ap.parse_args()

    # force the virtual CPU mesh BEFORE jax initializes. A site plugin
    # can force-register an accelerator platform and win over the env
    # var (tests/conftest.py documents the same issue), so pin via
    # jax.config as well and assert — a curve silently measured on one
    # real chip sliced four ways would be meaningless.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    assert jax.default_backend() == "cpu" and jax.device_count() == 8, (
        f"need the 8-device CPU mesh, got {jax.device_count()} "
        f"{jax.default_backend()} device(s)"
    )
    import jax.numpy as jnp
    import numpy as np

    from kafka_assignment_optimizer_tpu.models.instance import (
        build_instance,
    )
    from kafka_assignment_optimizer_tpu.parallel.mesh import (
        best_of,
        make_mesh,
        mesh_snapshot,
        solve_on_mesh,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu import arrays
    from kafka_assignment_optimizer_tpu.solvers.tpu.arrays import (
        geometric_temps,
    )
    from kafka_assignment_optimizer_tpu.solvers.tpu.seed import greedy_seed
    from kafka_assignment_optimizer_tpu.utils import gen

    kw = (
        dict(n_brokers=48, n_topics_low=16, n_topics_high=14,
             parts_per_topic=20)
        if args.smoke else {}
    )
    sc = gen.SCENARIOS["adversarial"](**kw)
    inst = build_instance(sc.current, sc.broker_list, sc.topology,
                          sc.target_rf)
    m = arrays.from_instance(inst)
    # the greedy seed is already move-optimal on this class (that is
    # what the reseat racer exploits), so a curve from it is flat at
    # every device count — there is nothing left for the search to
    # buy. Scramble LEADERSHIP instead: roll each partition's slot
    # order by a random amount. Membership — and with it the replica
    # move count — is unchanged, but leader counts skew out of band
    # and preservation weight drops, so the population must both
    # repair feasibility and re-earn weight: the regime where
    # independent chains + ICI migration show their value.
    seed = np.asarray(greedy_seed(inst)).copy()
    rng = np.random.default_rng(args.scramble_seed)
    for p in range(inst.num_parts):
        r = int(inst.rf[p])
        seed[p, :r] = np.roll(seed[p, :r], int(rng.integers(0, r)))
    seed_w = int(inst.preservation_weight(seed))
    seed = jnp.asarray(seed, jnp.int32)
    temps = geometric_temps(2.0, 0.02, args.sweeps)
    ub = inst.weight_upper_bound()
    lb = inst.move_lower_bound_exact()

    rows = []
    for n_dev in (1, 2, 4, 8):
        mesh = make_mesh(n_dev)
        t0 = time.perf_counter()
        _st, pop_a, pop_k, _curve = solve_on_mesh(
            m, seed, jax.random.PRNGKey(7), mesh,
            chains_per_device=args.chains_per_device,
            rounds=args.sweeps, steps_per_round=1,
            engine="sweep", temps=temps,
        )
        best_a, best_k = best_of(pop_a, pop_k)
        wall = time.perf_counter() - t0
        best_np = np.asarray(best_a)
        rows.append({
            "n_devices": n_dev,
            "chains_total": n_dev * args.chains_per_device,
            "wall_s": round(wall, 2),
            "objective": int(inst.preservation_weight(best_np)),
            "moves": int(inst.move_count(best_np)),
            "feasible": bool(inst.is_feasible(best_np)),
        })
        print(f"[multichip] {rows[-1]}", file=sys.stderr)

    out = {
        "scenario": sc.name,
        "smoke": args.smoke,
        "brokers": inst.num_brokers,
        "partitions": inst.num_parts,
        "sweeps": args.sweeps,
        "chains_per_device": args.chains_per_device,
        "seed": "greedy + per-partition leadership scramble",
        "seed_weight": seed_w,
        "weight_upper_bound": int(ub),
        "move_lower_bound": int(lb),
        "platform": jax.devices()[0].platform,
        # process/mesh topology (docs/MESH.md): single-chain curves run
        # the default chains-only split; a multi-process or lane-split
        # artifact is incomparable to this one (obs/regress.py)
        "n_processes": jax.process_count(),
        "process_index": jax.process_index(),
        "mesh_axes": dict(mesh_snapshot()["axes"]),
        "note": (
            "virtual 8-device CPU mesh on a 1-core host: devices "
            "timeshare, so wall_s grows with n_devices HERE; on a real "
            "v5e-8 each device anneals its chains concurrently and "
            "wall stays ~flat while quality follows this curve"
        ),
        "curve": rows,
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
